"""Executor — binds a Symbol to devices and runs it.

Parity target: include/mxnet/executor.h + src/executor/graph_executor.cc.

TPU-native design (SURVEY §7): ``bind`` lowers the ENTIRE symbolic graph
to one jitted XLA computation. This single design move replaces the
reference's NNVM pass pipeline:
- PlanMemory/inplace/pooling  → XLA buffer assignment + donation
- AttachOpExecs + engine push per node → one compiled executable
- op bulking (BulkTrainingOpSegs)      → whole-program fusion
- InferShape pass → jax.eval_shape at trace time (+ symbol/infer hooks)
- gradient graph (pass::Gradient)      → jax.vjp over the traced program

``forward`` runs the forward executable; ``backward`` / the fused
``forward_backward`` run a forward+vjp executable (compiled once per
train/eval mode and input-shape signature; the shape-signature cache is
jax.jit's own, which is what CachedOp::SetForwardGraph re-implemented).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from .base import MXNetError
from .context import Context
from . import ops as _ops

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, batch_args=None, group2ctx=None,
                 cw_bucket=None):
        from .ndarray import NDArray, zeros as nd_zeros

        self._symbol = symbol
        # shape-bucketing identity: when this executor is one bucket of
        # a ladder (BucketingModule / bucketed fit), its programs stage
        # under the bucket's own compile-watch site (`bucketing:<key>`,
        # statics carry the key) so the ladder is a FIXED program set —
        # site_stats("bucketing") counts it and a bucket switch is
        # specialization, never storm churn.
        self._cw_bucket = cw_bucket
        # Multi-context bind = in-program data parallelism: ONE compiled
        # program over a 'dp' device mesh; batch args are sharded on dim
        # 0, params/aux replicated, and XLA's SPMD partitioner inserts
        # the gradient psum the reference routed through KVStore
        # (executor_group.py:281 decide_slices + kvstore_dist.h:44).
        self._ctx_arg = ctx
        if isinstance(ctx, (list, tuple)) and len(ctx) > 1:
            ctxs = [c if isinstance(c, Context) else Context(c)
                    for c in ctx]
            self._ctx = ctxs[0]
            # The reference tolerates repeated contexts (one executor
            # per list entry on the same GPU); a mesh needs distinct
            # devices, and deduping is numerically equivalent since the
            # program computes the global batch either way.
            from .parallel.mesh import dp_mesh, distinct_devices
            devices = distinct_devices(ctxs)
            self._mesh = dp_mesh(devices) if len(devices) > 1 else None
        else:
            if isinstance(ctx, (list, tuple)):
                ctx = ctx[0]
            self._ctx = ctx if isinstance(ctx, Context) else Context(ctx)
            self._mesh = None
        self._batch_args = set(batch_args or ())
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()

        # normalize args
        if isinstance(args, dict):
            missing = [n for n in self.arg_names if n not in args]
            if missing:
                raise MXNetError("bind: missing arguments %s" % missing)
            self.arg_arrays = [args[n] for n in self.arg_names]
        else:
            args = list(args)
            if len(args) != len(self.arg_names):
                raise MXNetError(
                    "bind: expected %d args, got %d"
                    % (len(self.arg_names), len(args)))
            self.arg_arrays = args

        # grad_req normalize
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self.arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null")
                              for n in self.arg_names}
        if args_grad is None:
            args_grad = {}
            for n in self.arg_names:
                if self._grad_req[n] != "null":
                    self._grad_req[n] = "null"
        if isinstance(args_grad, dict):
            self.grad_arrays = [args_grad.get(n) for n in self.arg_names]
        else:
            args_grad = list(args_grad)
            self.grad_arrays = list(args_grad) + \
                [None] * (len(self.arg_names) - len(args_grad))
        for n, g in zip(self.arg_names, self.grad_arrays):
            if g is None and self._grad_req.get(n, "null") != "null":
                self._grad_req[n] = "null"

        # aux states
        if aux_states is None:
            aux_states = []
        if isinstance(aux_states, dict):
            self.aux_arrays = [aux_states[n] for n in self.aux_names]
        else:
            self.aux_arrays = list(aux_states)
        if len(self.aux_arrays) != len(self.aux_names):
            raise MXNetError("bind: expected %d aux states, got %d"
                             % (len(self.aux_names), len(self.aux_arrays)))

        self.arg_dict = dict(zip(self.arg_names, self.arg_arrays))
        self.grad_dict = {n: g for n, g in zip(self.arg_names,
                                               self.grad_arrays)}
        self.aux_dict = dict(zip(self.aux_names, self.aux_arrays))

        # FSDP (MXNET_PARAM_SHARD=1) on a mesh bind: non-batch args
        # rule-resolve to sharded placements (parallel.sharding_rules)
        # — _dp_place keeps them resident at 1/N and the compiled
        # programs gather them at entry. NDArray handles keep their
        # logical shapes, so a param the rules would need to PAD stays
        # replicated here (with a one-time telemetry note naming it);
        # the padded-storage form lives in DistributedTrainer.
        self._param_shard_plans = None
        if self._mesh is not None:
            from .parallel.sharding_rules import (ShardingRules,
                                                  param_shard_enabled)
            if param_shard_enabled():
                rules = ShardingRules(self._mesh)
                plans = {}
                for n, arr in zip(self.arg_names, self.arg_arrays):
                    if n in self._batch_args:
                        continue
                    pl = rules.plan(n, arr.shape)
                    if not pl.sharded:
                        continue
                    if pl.padded:
                        from . import telemetry
                        telemetry.note("param_shard_fallback:%s" % n)
                        continue
                    plans[n] = pl
                self._param_shard_plans = plans or None

        # persistent output buffers
        self.outputs = [None] * len(self._symbol._outputs)
        self._fns: Dict[Any, Any] = {}
        self._rng_count = sum(
            1 for n in symbol._topo_nodes()
            if n.op is not None and n.op.needs_rng)
        self._monitor_callback = None
        self._build_plan()
        # ctx_group placement: partition into device-pinned segment
        # programs (placement.py; ref graph_executor.cc:907
        # AssignContext) when group2ctx names any group the graph uses
        self._grouped = None
        if group2ctx:
            has_groups = any(
                n._extra_attrs.get("ctx_group") in group2ctx
                for n in getattr(self, "_plan_nodes", []))
            if has_groups:
                if self._mesh is not None:
                    raise MXNetError(
                        "group2ctx placement cannot be combined with a "
                        "multi-context data-parallel bind")
                from .placement import GroupedProgram
                self._grouped = GroupedProgram(self, group2ctx)

    # -- graph plan ------------------------------------------------------
    def _build_plan(self):
        nodes = self._symbol._topo_nodes()
        self._nodes = nodes
        arg_pos = {n: i for i, n in enumerate(self.arg_names)}
        aux_pos = {n: i for i, n in enumerate(self.aux_names)}
        self._plan = []
        node_slot = {}
        slot = 0
        rng_slot = 0
        for nd_ in nodes:
            if nd_.is_variable():
                if nd_.name in aux_pos:
                    src = ("aux", aux_pos[nd_.name])
                elif nd_.name in arg_pos:
                    src = ("arg", arg_pos[nd_.name])
                else:
                    raise MXNetError("unbound variable %s" % nd_.name)
                node_slot[id(nd_)] = ("var", src)
            else:
                nattrs = _ops.normalize_attrs(nd_.op, nd_.attrs)
                bindings = []
                for (s, i) in nd_.inputs:
                    kind, ref = node_slot[id(s)]
                    if kind == "var":
                        bindings.append(ref)
                    else:
                        bindings.append(("res", ref, i))
                rs = None
                if nd_.op.needs_rng:
                    rs = rng_slot
                    rng_slot += 1
                # aux writeback mapping: mutable input idx → aux slot
                aux_wb = []
                for mi in nd_.op.mutable_inputs:
                    if mi < len(nd_.inputs):
                        src, _ = nd_.inputs[mi]
                        if src.is_variable() and src.name in aux_pos:
                            aux_wb.append(aux_pos[src.name])
                        else:
                            aux_wb.append(None)
                self._plan_names = getattr(self, "_plan_names", [])
                self._plan_names.append(nd_.name)
                self._plan_nodes = getattr(self, "_plan_nodes", [])
                self._plan_nodes.append(nd_)
                self._plan.append((nd_.op, nattrs, tuple(bindings), rs,
                                   aux_wb, slot))
                node_slot[id(nd_)] = ("res", slot)
                slot += 1
        self._head_refs = []
        for (n, i) in self._symbol._outputs:
            kind, ref = node_slot[id(n)]
            if kind == "var":
                self._head_refs.append((ref[0], ref[1], 0))
            else:
                self._head_refs.append(("res", ref, i))
        self._grad_positions = [i for i, n in enumerate(self.arg_names)
                                if self._grad_req.get(n, "null") != "null"]
        self._plan_bias_defer()

    def _plan_bias_defer(self):
        """Peephole: Convolution-with-bias whose SOLE consumer is a
        train-mode channel-axis BatchNorm.

        Normalization makes the conv bias a no-op on the normalized
        output: BN subtracts the batch mean, which contains the bias, so
        ``BN(conv(x)+b)`` ≡ ``BN(conv(x))`` with the batch/running means
        shifted by exactly ``b`` (variance is shift-invariant, and the
        bias gradient is the per-channel sum of BN's input gradient,
        which is identically zero). XLA cannot discover this algebra, so
        without the rewrite every train step pays a full HBM pass per
        biased conv to reduce a gradient that is mathematically zero —
        ~10% of a ResNet-50 train step (the model zoo's BottleneckV1
        keeps the reference's biased 1x1 convs,
        ref python/mxnet/gluon/model_zoo/vision/resnet.py:108).

        The compiled train program runs the conv biasless and adds the
        bias back into the BatchNorm mean outputs (head mean when
        ``output_mean_var``, and the ``moving_mean`` writeback), keeping
        checkpoint/inference semantics identical. Eval-mode programs are
        untouched — with running stats the bias is live.
        """
        consumers: Dict[tuple, list] = {}
        for pi, (op, nattrs, bindings, rs, aux_wb, slot) \
                in enumerate(self._plan):
            for b in bindings:
                if b[0] == "res":
                    consumers.setdefault((b[1], b[2]), []).append(pi)
        for h in self._head_refs:
            if h[0] == "res":
                consumers.setdefault((h[1], h[2]), []).append("head")
        self._bias_defer = {}
        for pi, (op, nattrs, bindings, rs, aux_wb, slot) \
                in enumerate(self._plan):
            if op.name != "Convolution" or bool(nattrs.get("no_bias")) \
                    or len(bindings) != 3:
                continue
            cons = consumers.get((slot, 0), [])
            if len(cons) != 1 or cons[0] == "head":
                continue
            bn_pi = cons[0]
            bn_op, bn_attrs, bn_bind, _, _, _ = self._plan[bn_pi]
            if bn_op.name != "BatchNorm" \
                    or int(bn_attrs.get("axis", 1)) != 1 \
                    or bool(bn_attrs.get("use_global_stats", False)) \
                    or bn_bind[0] != ("res", slot, 0):
                continue
            self._bias_defer[pi] = (bn_pi, bindings[2])

    def _make_graph_fn(self, is_train, allow_rewrites=True):
        plan = self._plan
        plan_names = getattr(self, "_plan_names", [])
        head_refs = self._head_refs
        n_aux = len(self.aux_names)
        # the monitored eager path must see the model's DEFINED per-op
        # values (conv output incl. bias), not the rewritten program's
        bias_defer = self._bias_defer \
            if (is_train and allow_rewrites) else {}
        # BN plan-index -> (bias binding, BN momentum) for the mean
        # corrections
        bn_bias = {bn_pi: (bias_b,
                           float(self._plan[bn_pi][1].get("momentum", 0.9)))
                   for bn_pi, bias_b in bias_defer.values()}
        def run(arg_vals, aux_vals, rng_keys):
            results: List[tuple] = []
            new_aux = list(aux_vals)
            def resolve(b):
                if b[0] == "arg":
                    return arg_vals[b[1]]
                if b[0] == "aux":
                    return new_aux[b[1]]
                return results[b[1]][b[2]]
            for pi, (op, nattrs, bindings, rs, aux_wb, slot) \
                    in enumerate(plan):
                if pi in bias_defer:
                    bindings = bindings[:2]
                vals = [resolve(b) for b in bindings]
                attrs = nattrs
                if pi in bias_defer:
                    attrs = dict(attrs, no_bias=True)
                if "__train__" in op.defaults:
                    attrs = dict(attrs, __train__=is_train)
                if rs is not None:
                    out = op.forward(attrs, *vals, rng=rng_keys[rs])
                else:
                    out = op.forward(attrs, *vals)
                if not isinstance(out, (tuple, list)):
                    out = (out,)
                if pi in bn_bias:
                    bias_b, bn_mom = bn_bias[pi]
                    out = self._bn_add_bias(out, resolve(bias_b), bn_mom,
                                            op.resolve_num_outputs(attrs))
                n_out = op.resolve_num_outputs(attrs)
                if getattr(self, "_tap_eager", False):
                    # per-op monitor taps: only reached on the eager
                    # interpreted debug path (_forward_monitored) —
                    # values here are concrete arrays
                    for oi in range(n_out):
                        tag = plan_names[pi] + "_output" + \
                            (str(oi) if n_out > 1 else "")
                        self._host_tap(tag, out[oi])
                results.append(tuple(out[:n_out]))
                extras = out[n_out:]
                for wb, val in zip(aux_wb, extras):
                    if wb is not None:
                        new_aux[wb] = val
            outs = []
            for h in head_refs:
                if h[0] == "arg":
                    outs.append(arg_vals[h[1]])
                elif h[0] == "aux":
                    outs.append(new_aux[h[1]])
                else:
                    outs.append(results[h[1]][h[2]])
            return tuple(outs), tuple(new_aux)

        return run

    @staticmethod
    def _bn_add_bias(out, bias, momentum, n_out):
        """Shift a BatchNorm node's mean outputs by a deferred conv
        bias (see ``_plan_bias_defer``): the head batch-mean (when
        output_mean_var) shifts by the full bias, while the moving_mean
        writeback blends ``new = momentum*old + (1-momentum)*batch_mean``
        so only the ``(1-momentum)`` share of the bias enters per step —
        the recurrence then converges to exactly ``true_mean + bias``.
        Variance is shift-invariant; the normalized output needs no
        correction. The bias is stop-gradient here: the BN core's
        custom VJP already treats the mean/var heads as
        non-differentiable (ops/nn.py _bn_train_core), so the
        un-rewritten program gives the bias no gradient through the
        mean head either — without the stop, the rewritten program
        would leak the head cotangent straight into the bias."""
        from jax import lax as _lax
        bias = _lax.stop_gradient(bias)
        out = list(out)
        if n_out == 3:
            out[1] = out[1] + bias.astype(out[1].dtype)
        out[n_out] = out[n_out] \
            + ((1.0 - momentum) * bias).astype(out[n_out].dtype)
        return tuple(out)

    @property
    def cw_cache_token(self):
        """Content fingerprint of the bound graph for the persistent
        compile cache: site + statics + argument signature cannot tell
        two different symbols with identical shapes apart — the graph
        hash can. None when the graph will not serialize (the program
        then opts out of the disk cache rather than risking a
        collision) or when no cache is active (the tojson+sha256 is
        only worth paying when something will read it)."""
        if not hasattr(self, "_cw_token"):
            from . import compile_cache
            from .compile_cache import graph_token
            if not compile_cache.enabled():
                return None        # don't latch: cache may enable later
            try:
                self._cw_token = graph_token(self._symbol.tojson())
            except Exception:
                self._cw_token = None
        return self._cw_token

    def _get_fn(self, kind, is_train, raw=False):
        """The compiled (or with ``raw=True`` the traceable, unjitted)
        forward / fwdbwd program. ``raw`` is for callers composing the
        program inside their OWN jit (a scanned train loop, a pipeline
        stage): nesting the jitted form is legal but a nested jit cannot
        carry compiler options, and the raw callable traces straight
        into the outer program."""
        import jax
        if raw and self._mesh is not None:
            # the jitted form's out_shardings keep aux/grads replicated
            # on the dp mesh; a raw caller's own jit would lose that
            # invariant and later eager math would mix device sets
            raise MXNetError(
                "_get_fn(raw=True) is not supported on a multi-device "
                "bind; jit the executor's compiled fn or bind one ctx")
        key = (kind, is_train, bool(raw))
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        from . import compile_cache, compile_watch
        from .engine import compiler_options
        copts = compiler_options(self._ctx)
        run = self._make_graph_fn(is_train)
        # env-driven cache activation must precede the token read (the
        # token is only computed while a cache is live); a live cache
        # with an unhashable graph opts this program out entirely
        compile_cache.maybe_enable()
        ctoken = self.cw_cache_token
        cache_ok = ctoken is not None
        site = "executor:%s:%s" % (kind, "train" if is_train else "eval")
        rep = None
        statics = None
        if self._cw_bucket is not None:
            from .bucketing.ladder import bucket_site
            site = bucket_site(self._cw_bucket)
            statics = ("bucket", kind, is_train, self._cw_bucket)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(self._mesh, P())
        gather_entry = None
        if rep is not None and self._param_shard_plans:
            # FSDP entry gather: pin the sharded params to replicated
            # FIRST inside the program (the partitioner's just-in-time
            # all-gather). The fwdbwd vjp is taken over the GATHERED
            # values — the gather sits outside the differentiated
            # function, so the cotangents (and every downstream op)
            # are the identical traced computation as a replicated
            # bind. Distinct compile-watch identity: a replicated↔
            # sharded flip is a new program, not churn of this site.
            wsc = jax.lax.with_sharding_constraint
            shard_pos = frozenset(
                i for i, n in enumerate(self.arg_names)
                if n in self._param_shard_plans)
            statics = (statics or ()) + ("param_shard",)

            def gather_entry(arg_vals):
                return tuple(wsc(v, rep) if i in shard_pos else v
                             for i, v in enumerate(arg_vals))
        if kind == "fwd":
            if gather_entry is not None:
                inner_run = run

                def run(arg_vals, aux_vals, rng_keys):
                    return inner_run(gather_entry(arg_vals), aux_vals,
                                     rng_keys)
            if raw:
                fn = run
            elif rep is not None:
                # outputs auto-sharded; updated aux replicated so eager
                # math on them never mixes device sets
                fn = compile_watch.jit(
                    run, site, describe=self._cw_describe,
                    statics=statics, cache=cache_ok,
                    cache_token=ctoken,
                    out_shardings=(None, rep), compiler_options=copts)
            else:
                fn = compile_watch.jit(run, site,
                                       describe=self._cw_describe,
                                       statics=statics, cache=cache_ok,
                                       cache_token=ctoken,
                                       compiler_options=copts)
        else:
            gpos = self._grad_positions

            def fwdbwd(arg_vals, aux_vals, rng_keys, out_grads):
                if gather_entry is not None:
                    # gather BEFORE the vjp: the diff variables are
                    # the full logical values, exactly as on a
                    # replicated bind
                    arg_vals = gather_entry(arg_vals)
                def f(gvals):
                    full = list(arg_vals)
                    for p, v in zip(gpos, gvals):
                        full[p] = v
                    outs, new_aux = run(tuple(full), aux_vals, rng_keys)
                    return outs, new_aux
                outs, vjp_fn, new_aux = jax.vjp(
                    f, [arg_vals[p] for p in gpos], has_aux=True)
                grads, = vjp_fn(tuple(out_grads))
                return outs, new_aux, grads

            if raw:
                fn = fwdbwd
            elif rep is not None:
                # grads replicated = the in-program allreduce
                fn = compile_watch.jit(
                    fwdbwd, site, describe=self._cw_describe,
                    statics=statics, cache=cache_ok,
                    cache_token=ctoken,
                    out_shardings=(None, rep, rep),
                    compiler_options=copts)
            else:
                fn = compile_watch.jit(fwdbwd, site,
                                       describe=self._cw_describe,
                                       statics=statics, cache=cache_ok,
                                       cache_token=ctoken,
                                       compiler_options=copts)
        self._fns[key] = fn
        return fn

    def _cw_describe(self, arg_vals, aux_vals, rng_keys, out_grads=None):
        """compile_watch describe hook: name the compiled program's
        argument leaves with the symbol's own arg/aux names, so a
        recompile-cause diff says "data: f32[32,784] -> f32[48,784]"
        instead of a positional index."""
        from .compile_watch import describe_arrays
        d = describe_arrays(self.arg_names, arg_vals)
        d.update(describe_arrays(["aux:%s" % n for n in self.aux_names],
                                 aux_vals))
        if rng_keys:
            d.update(describe_arrays(
                ["rng%d" % i for i in range(len(rng_keys))], rng_keys))
        if out_grads is not None:
            d.update(describe_arrays(
                ["out_grad:%s" % n for n in self.output_names],
                out_grads))
        return d

    # -- execution -------------------------------------------------------
    def _dp_shardings(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return (NamedSharding(self._mesh, P()),
                NamedSharding(self._mesh, P("dp")))

    def _dp_place(self, args, aux):
        """Commit persistent buffers to their mesh shardings: batch args
        split on dim 0 over 'dp', everything else replicated. The NDArray
        handles are updated in place so subsequent eager math (optimizer
        updates on weights+grads) stays within one device set."""
        import jax
        rep, shard = self._dp_shardings()
        n_dp = self._mesh.devices.size
        plans = self._param_shard_plans
        placed = []
        for name, arr, val in zip(self.arg_names, self.arg_arrays, args):
            if name in self._batch_args and val.ndim >= 1 \
                    and val.shape[0] % n_dp == 0:
                tgt = shard
            elif plans is not None and name in plans:
                # FSDP residency: the param lives as its 1/N shard
                # between dispatches; an eager update that returned a
                # differently-placed value is re-sliced here (local —
                # the value is already materialized on these devices)
                tgt = plans[name].sharding(self._mesh)
            else:
                tgt = rep
            if val.sharding != tgt:
                val = jax.device_put(val, tgt)
                arr._set_data(val)
            placed.append(val)
        placed_aux = []
        for arr, val in zip(self.aux_arrays, aux):
            if val.sharding != rep:
                val = jax.device_put(val, rep)
                arr._set_data(val)
            placed_aux.append(val)
        return tuple(placed), tuple(placed_aux)

    def _gather_inputs(self, kwargs):
        from .ndarray import NDArray
        if kwargs:
            for k, v in kwargs.items():
                if k not in self.arg_dict:
                    raise MXNetError("unknown argument %s" % k)
                if isinstance(v, NDArray):
                    self.arg_dict[k]._set_data(v._data)
                else:
                    import jax.numpy as jnp
                    self.arg_dict[k]._set_data(
                        jnp.asarray(v, dtype=self.arg_dict[k].dtype))
        args = tuple(a._data for a in self.arg_arrays)
        aux = tuple(a._data for a in self.aux_arrays)
        if self._mesh is not None:
            args, aux = self._dp_place(args, aux)
        return args, aux

    def _rngs(self):
        from . import random as _random
        keys = tuple(_random.new_key() for _ in range(self._rng_count))
        if self._mesh is not None and keys:
            import jax
            rep, _ = self._dp_shardings()
            keys = tuple(jax.device_put(k, rep) for k in keys)
        return keys

    def _store_outputs(self, outs):
        from .ndarray import NDArray
        for i, o in enumerate(outs):
            if self.outputs[i] is None:
                self.outputs[i] = NDArray(o, ctx=self._ctx)
            else:
                self.outputs[i]._set_data(o)

    def _store_aux(self, new_aux):
        for arr, val in zip(self.aux_arrays, new_aux):
            arr._set_data(val)

    def forward(self, is_train=False, **kwargs):
        args, aux = self._gather_inputs(kwargs)
        rngs = self._rngs()
        self._last_rngs = rngs  # backward() must replay this draw
        if self._monitor_callback is not None and \
                getattr(self, "_monitor_all", False):
            # per-op monitoring runs the plan EAGERLY (interpreted,
            # like the reference's NaiveEngine debug mode) so every
            # intermediate can be tapped on any backend — the tunnel's
            # PJRT has no host-callback support inside compiled code
            self._tap_eager = True
            try:
                run = self._make_graph_fn(bool(is_train),
                                          allow_rewrites=False)
                outs, new_aux = run(args, aux, rngs)
            finally:
                self._tap_eager = False
            self._store_outputs(outs)
            if is_train:
                self._store_aux(new_aux)
            return self.outputs
        if self._grouped is not None:
            outs, new_aux = self._grouped.forward(args, aux, rngs,
                                                  bool(is_train))
        else:
            fn = self._get_fn("fwd", bool(is_train))
            outs, new_aux = fn(args, aux, rngs)
        self._store_outputs(outs)
        if is_train:
            self._store_aux(new_aux)
        if self._monitor_callback is not None:
            self._run_monitor()
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        self.forward_backward(out_grads=out_grads, is_train=is_train,
                              _refresh_outputs=True, _reuse_rngs=True)

    def forward_backward(self, out_grads=None, is_train=True,
                         _refresh_outputs=True, _reuse_rngs=False,
                         **kwargs):
        """Fused forward+backward in ONE XLA computation (the TPU
        replacement for the reference's overlap of backprop with engine-
        scheduled gradient reduction).

        When invoked through ``backward()`` the RNG keys of the
        caller's last ``forward()`` are replayed so stochastic ops
        (Dropout, rrelu) are differentiated at the SAME random draw the
        caller observed — the reference guarantees this by construction
        since its backward consumes stored forward activations.
        """
        import jax.numpy as jnp
        from .ndarray import NDArray
        if not self._grad_positions:
            # nothing requires grad: just forward
            self.forward(is_train=is_train, **kwargs)
            return
        args, aux = self._gather_inputs(kwargs)
        fn = None if self._grouped is not None \
            else self._get_fn("fwdbwd", bool(is_train))
        if out_grads is None:
            ogs = tuple(
                jnp.ones(tuple(s.shape), s.dtype)
                for s in self._out_structs(args, aux))
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            ogs = tuple(g._data for g in out_grads)
        rngs = getattr(self, "_last_rngs", None) \
            if _reuse_rngs else None
        if rngs is None:
            rngs = self._rngs()
        self._last_rngs = None  # one replay per forward
        if self._grouped is not None:
            outs, new_aux, grads = self._grouped.forward_backward(
                args, aux, rngs, ogs)
        else:
            outs, new_aux, grads = fn(args, aux, rngs, ogs)
        if _refresh_outputs:
            self._store_outputs(outs)
        if is_train:
            self._store_aux(new_aux)
        for p, g in zip(self._grad_positions, grads):
            name = self.arg_names[p]
            tgt = self.grad_arrays[p]
            if tgt is None:
                continue
            if self._grad_req[name] == "add":
                td = tgt._data
                if self._mesh is not None and td.sharding != g.sharding:
                    # first accumulation: the zeros buffer was created
                    # pre-mesh on one device; move it to the grad's
                    # (replicated) sharding before the eager add
                    import jax
                    td = jax.device_put(td, g.sharding)
                tgt._set_data(td + g)
            else:
                tgt._set_data(g)
        if self._monitor_callback is not None:
            self._run_monitor()

    def fused_plan(self):
        """The pieces the fused train-step executor (fused_step.py)
        composes into ITS OWN jit: the raw (unjitted) train-mode
        fwd+bwd program, the grad-carrying arg positions, and the
        traced output structs (for the default all-ones cotangents).
        Raises on a multi-device bind — raw tracing is unsupported
        there and the caller falls back to the eager path."""
        fn = self._get_fn("fwdbwd", True, raw=True)
        args = tuple(a._data for a in self.arg_arrays)
        aux = tuple(a._data for a in self.aux_arrays)
        return fn, list(self._grad_positions), self._out_structs(args, aux)

    def _out_structs(self, args, aux):
        import jax
        key = ("ostruct", tuple((a.shape, str(a.dtype)) for a in args))
        cached = self._fns.get(key)
        if cached is None:
            run = self._make_graph_fn(True)
            rngs = self._rngs() if self._rng_count else ()
            outs, _ = jax.eval_shape(run, args, aux, rngs)
            cached = outs
            self._fns[key] = cached
        return cached

    # -- misc API parity -------------------------------------------------
    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return an executor for new input shapes, sharing parameters."""
        from .ndarray import zeros as nd_zeros
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = []
        for name, arr, shape in zip(self.arg_names, self.arg_arrays,
                                    arg_shapes):
            if tuple(arr.shape) == tuple(shape):
                new_args.append(arr)
            else:
                new_args.append(nd_zeros(shape, ctx=self._ctx,
                                         dtype=arr.dtype))
        grads = {}
        for name, g in zip(self.arg_names, self.grad_arrays):
            if g is not None:
                idx = self.arg_names.index(name)
                if tuple(g.shape) == tuple(arg_shapes[idx]):
                    grads[name] = g
                else:
                    grads[name] = nd_zeros(arg_shapes[idx], ctx=self._ctx,
                                           dtype=g.dtype)
        return Executor(self._symbol, self._ctx_arg, new_args, grads,
                        self._grad_req, self.aux_arrays,
                        batch_args=self._batch_args,
                        cw_bucket=self._cw_bucket)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._set_data(
                    arr.astype(self.arg_dict[name].dtype)._data)
            elif not allow_extra_params:
                raise MXNetError("Found name \"%s\" that is not in the "
                                 "arguments" % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    self.aux_dict[name]._set_data(
                        arr.astype(self.aux_dict[name].dtype)._data)
                elif not allow_extra_params:
                    raise MXNetError("Found name \"%s\" that is not in the "
                                     "auxiliary states" % name)

    def set_monitor_callback(self, callback, monitor_all=False):
        """Per-op taps (monitor_all) run on the eager interpreted path;
        compiled programs are untouched, so no cache invalidation."""
        self._monitor_callback = callback
        self._monitor_all = monitor_all

    def _host_tap(self, name, value):
        """jax.debug.callback target: value arrives as host numpy."""
        from .ndarray import array as nd_array
        cb = self._monitor_callback
        if cb is not None:
            cb(name, nd_array(value))

    def _run_monitor(self):
        for name, out in zip(self.output_names, self.outputs):
            self._monitor_callback(name, out)

    @property
    def output_dict(self):
        return dict(zip(self.output_names, self.outputs))

    def debug_str(self):
        lines = ["Symbol Outputs:"]
        for n in self.output_names:
            lines.append("\toutput=%s" % n)
        for op, nattrs, bindings, rs, aux_wb, slot in self._plan:
            lines.append("Op:%s" % op.name)
        return "\n".join(lines)
