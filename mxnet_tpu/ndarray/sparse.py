"""Sparse NDArrays: ``csr`` and ``row_sparse`` storage.

Parity surface: python/mxnet/ndarray/sparse.py (CSRNDArray:287,
RowSparseNDArray:561, csr_matrix:825, row_sparse_array:1020) and the
C++ storage kinds in include/mxnet/ndarray.h:61-65 plus
src/operator/tensor/cast_storage-inl.h.

TPU-native design (SURVEY §7 hard part #4): a sparse array is a pair of
dense device arrays (values + integer aux arrays) and a logical dense
shape. Compute lowers to gather/scatter/segment-sum — the operations the
TPU does well — instead of the reference's CPU/GPU sparse kernels:

- ``dot(csr, dense)``            → take + segment_sum over row ids
- ``dot(csr, dense, trans_a)``   → take + segment_sum over col ids
- ``cast_storage``               → scatter (to dense) / host row-scan
                                   (to sparse; nnz is data-dependent, so
                                   the conversion syncs — documented)
- ``retain``                     → gather of kept rows
- optimizer lazy update          → gather rows, update, scatter (see
                                   optimizer.py sparse paths)

Aux index arrays use int64 like the reference's default aux dtype.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray, array as _dense_array, zeros as _dense_zeros

__all__ = ["BaseSparseNDArray", "CSRNDArray", "RowSparseNDArray",
           "csr_matrix", "row_sparse_array", "cast_storage", "retain",
           "dot", "zeros", "empty", "array", "add", "subtract", "multiply",
           "divide"]


def _jnp():
    import jax.numpy as jnp
    return jnp


class BaseSparseNDArray(NDArray):
    """Common behavior of csr/row_sparse arrays.

    ``_data`` (the dense buffer) intentionally raises: any code path
    that reaches for it must handle sparse explicitly (the reference
    raises NotSupportedForSparseNDArray the same way).
    """

    def __init__(self, shape, ctx=None):
        self._shape = tuple(int(s) for s in shape)
        self._ctx = ctx if ctx is not None else current_context()
        self.grad = None
        self._grad_req = "null"
        self._tape_node = None
        self._tape_index = 0
        self._fresh_grad = False

    # _data is a plain attribute on NDArray; property here shadows it.
    @property
    def _data(self):
        raise MXNetError(
            "%s has no dense buffer; use .data/.indices (and .indptr) "
            "or tostype('default')" % type(self).__name__)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self._ctx

    ctx = context

    def __repr__(self):
        return "\n<%s %s @%s>" % (type(self).__name__,
                                  "x".join(str(s) for s in self._shape),
                                  self._ctx)

    def __len__(self):
        return self._shape[0]

    # -- unsupported dense API (parity: sparse.py:147-160) --------------
    def _not_supported(self, what):
        raise MXNetError("%s is not supported for %s"
                         % (what, type(self).__name__))

    def reshape(self, *shape, **kwargs):
        self._not_supported("reshape")

    def _at(self, idx):
        self._not_supported("_at")

    def _slice(self, start, stop):
        self._not_supported("_slice")

    # -- host/introspection ---------------------------------------------
    def asnumpy(self):
        return self._dense_np()

    def wait_to_read(self):
        self.data.wait_to_read()

    def copyto(self, other):
        from ..context import Context
        if isinstance(other, Context):
            return self._clone(ctx=other)
        if isinstance(other, BaseSparseNDArray):
            if other.stype != self.stype:
                raise MXNetError("copyto: storage type mismatch (%s vs %s)"
                                 % (self.stype, other.stype))
            other._assign_from(self)
            return other
        if isinstance(other, NDArray):
            other._set_data(self.tostype("default")._data)
            return other
        raise TypeError("copyto does not support type %s" % type(other))

    def copy(self):
        return self._clone()

    def astype(self, dtype, copy=True):
        c = self._clone()
        c._sp_data = c._sp_data.astype(dtype)
        return c

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        return self._clone(ctx=context)

    def check_format(self, full_check=True):
        self._check_format()

    # -- arithmetic: scalar ops keep sparsity, the rest densify ----------
    def _scalar_sparsity_op(self, other, fn):
        if isinstance(other, (int, float)):
            c = self._clone()
            c._sp_data = fn(c._sp_data, other)
            return c
        return None

    def __mul__(self, other):
        r = self._scalar_sparsity_op(other, lambda d, s: d * s)
        if r is not None:
            return r
        return _densify_binop(self, other, lambda a, b: a * b)

    __rmul__ = __mul__

    def __div__(self, other):
        return self.__truediv__(other)

    def __truediv__(self, other):
        r = self._scalar_sparsity_op(other, lambda d, s: d / s)
        if r is not None:
            return r
        return _densify_binop(self, other, lambda a, b: a / b)

    def __add__(self, other):
        same = self._same_structure_op(other, lambda a, b: a + b)
        if same is not None:
            return same
        return _densify_binop(self, other, lambda a, b: a + b)

    __radd__ = __add__

    def __sub__(self, other):
        same = self._same_structure_op(other, lambda a, b: a - b)
        if same is not None:
            return same
        return _densify_binop(self, other, lambda a, b: a - b)

    def __neg__(self):
        c = self._clone()
        c._sp_data = -c._sp_data
        return c

    def _same_structure_op(self, other, fn):
        return None  # overridden by RowSparseNDArray


def _densify_binop(lhs, rhs, fn):
    a = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) else lhs
    b = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) else rhs
    return fn(a, b)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference: sparse.py:287)."""

    def __init__(self, data, indices, indptr, shape, ctx=None):
        super().__init__(shape, ctx)
        if len(self._shape) != 2:
            raise MXNetError("csr requires a 2-D shape, got %s"
                             % (self._shape,))
        self._sp_data = data
        self._sp_indices = indices
        self._sp_indptr = indptr

    @property
    def stype(self):
        return "csr"

    @property
    def data(self):
        return self._sp_data

    @property
    def indices(self):
        return self._sp_indices

    @property
    def indptr(self):
        return self._sp_indptr

    @property
    def _aux_types(self):
        return [_np.dtype(_np.int64), _np.dtype(_np.int64)]

    def _clone(self, ctx=None):
        ctx = ctx or self._ctx
        return CSRNDArray(self._sp_data.copy(), self._sp_indices.copy(),
                          self._sp_indptr.copy(), self._shape, ctx=ctx)

    def _assign_from(self, other):
        self._sp_data = other._sp_data.copy()
        self._sp_indices = other._sp_indices.copy()
        self._sp_indptr = other._sp_indptr.copy()
        self._shape = other._shape

    def _check_format(self):
        indptr = self._sp_indptr.asnumpy()
        indices = self._sp_indices.asnumpy()
        if indptr.shape != (self._shape[0] + 1,):
            raise MXNetError("csr indptr length %s != rows+1" %
                             (indptr.shape,))
        if indptr[0] != 0 or indptr[-1] != indices.shape[0]:
            raise MXNetError("csr indptr endpoints invalid")
        if (_np.diff(indptr) < 0).any():
            raise MXNetError("csr indptr must be non-decreasing")
        if indices.size and (indices.min() < 0
                             or indices.max() >= self._shape[1]):
            raise MXNetError("csr indices out of bounds")

    def _dense_np(self):
        out = _np.zeros(self._shape, dtype=self._sp_data.dtype)
        data = self._sp_data.asnumpy()
        indices = self._sp_indices.asnumpy()
        indptr = self._sp_indptr.asnumpy()
        for i in range(self._shape[0]):
            out[i, indices[indptr[i]:indptr[i + 1]]] = \
                data[indptr[i]:indptr[i + 1]]
        return out

    def _row_ids(self):
        """Per-nnz row id (host-computed from indptr; static per batch)."""
        indptr = self._sp_indptr.asnumpy()
        return _np.repeat(_np.arange(self._shape[0]), _np.diff(indptr))

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            import jax.numpy as jnp
            dense = jnp.zeros(self._shape, dtype=self._sp_data.dtype)
            rows = jnp.asarray(self._row_ids())
            cols = self._sp_indices._data
            dense = dense.at[rows, cols].set(self._sp_data._data)
            return NDArray(dense, ctx=self._ctx)
        raise MXNetError("cast_storage from csr to %s is not supported"
                         % stype)

    def asscipy(self):
        import scipy.sparse as spsp
        return spsp.csr_matrix(
            (self._sp_data.asnumpy(), self._sp_indices.asnumpy(),
             self._sp_indptr.asnumpy()), shape=self._shape)

    def _same_structure_op(self, other, fn):
        # csr ⊕ csr keeps csr storage (reference elemwise_add(csr, csr)
        # returns csr). Pattern union is computed host-side from the
        # concrete index arrays; values merge on device.
        if not (isinstance(other, CSRNDArray)
                and other._shape == self._shape):
            return None
        import jax.numpy as jnp
        ncols = self._shape[1]
        a_keys = self._row_ids().astype(_np.int64) * ncols \
            + self._sp_indices.asnumpy().astype(_np.int64)
        b_keys = other._row_ids().astype(_np.int64) * ncols \
            + other._sp_indices.asnumpy().astype(_np.int64)
        union = _np.union1d(a_keys, b_keys)
        zero = jnp.zeros((len(union),), dtype=self._sp_data.dtype)
        a_full = zero.at[jnp.asarray(_np.searchsorted(union, a_keys))] \
            .set(self._sp_data._data)
        b_full = zero.at[jnp.asarray(_np.searchsorted(union, b_keys))] \
            .set(other._sp_data._data)
        out_data = fn(NDArray(a_full, ctx=self._ctx),
                      NDArray(b_full, ctx=self._ctx))
        u_rows = (union // ncols).astype(_np.int64)
        counts = _np.bincount(u_rows, minlength=self._shape[0])
        indptr = _np.concatenate([[0], _np.cumsum(counts)]) \
            .astype(_np.int64)
        return CSRNDArray(
            out_data,
            _dense_array((union % ncols), ctx=self._ctx, dtype=_np.int64),
            _dense_array(indptr, ctx=self._ctx, dtype=_np.int64),
            self._shape, ctx=self._ctx)

    def __getitem__(self, key):
        if isinstance(key, int):
            n = self._shape[0]
            if key < 0:
                key += n
            if not 0 <= key < n:
                raise IndexError("index %d out of bounds for %d rows"
                                 % (key, n))
            return self[key:key + 1]
        if isinstance(key, slice):
            start, stop, step = key.indices(self._shape[0])
            if step != 1:
                raise MXNetError("csr slicing supports step=1 only")
            stop = max(stop, start)
            indptr = self._sp_indptr.asnumpy()
            lo, hi = int(indptr[start]), int(indptr[stop])
            import jax.numpy as jnp
            return CSRNDArray(
                NDArray(self._sp_data._data[lo:hi], ctx=self._ctx),
                NDArray(self._sp_indices._data[lo:hi], ctx=self._ctx),
                NDArray(jnp.asarray(indptr[start:stop + 1] - lo),
                        ctx=self._ctx),
                (stop - start, self._shape[1]), ctx=self._ctx)
        raise MXNetError("csr indexing supports int/slice only")


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse array: a subset of rows is stored (reference:
    sparse.py:561). data shape = (nnz_rows,) + shape[1:]."""

    def __init__(self, data, indices, shape, ctx=None):
        super().__init__(shape, ctx)
        self._sp_data = data
        self._sp_indices = indices

    @property
    def stype(self):
        return "row_sparse"

    @property
    def data(self):
        return self._sp_data

    @property
    def indices(self):
        return self._sp_indices

    @property
    def _aux_types(self):
        return [_np.dtype(_np.int64)]

    def _clone(self, ctx=None):
        ctx = ctx or self._ctx
        return RowSparseNDArray(self._sp_data.copy(),
                                self._sp_indices.copy(),
                                self._shape, ctx=ctx)

    def _assign_from(self, other):
        self._sp_data = other._sp_data.copy()
        self._sp_indices = other._sp_indices.copy()
        self._shape = other._shape

    def _check_format(self):
        idx = self._sp_indices.asnumpy()
        if (_np.diff(idx) <= 0).any():
            raise MXNetError("row_sparse indices must be strictly "
                             "increasing")
        if idx.size and (idx.min() < 0 or idx.max() >= self._shape[0]):
            raise MXNetError("row_sparse indices out of bounds")
        if tuple(self._sp_data.shape[1:]) != self._shape[1:]:
            raise MXNetError("row_sparse data row shape mismatch")

    def _dense_np(self):
        out = _np.zeros(self._shape, dtype=self._sp_data.dtype)
        out[self._sp_indices.asnumpy()] = self._sp_data.asnumpy()
        return out

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            import jax.numpy as jnp
            dense = jnp.zeros(self._shape, dtype=self._sp_data.dtype)
            dense = dense.at[self._sp_indices._data].set(
                self._sp_data._data)
            return NDArray(dense, ctx=self._ctx)
        raise MXNetError("cast_storage from row_sparse to %s is not "
                         "supported" % stype)

    def retain(self, indices):
        return retain(self, indices)

    def __getitem__(self, key):
        if isinstance(key, slice):
            if key.start or key.step or (key.stop is not None
                                         and key.stop != self._shape[0]):
                raise MXNetError("row_sparse supports [:] slicing only")
            return self
        raise MXNetError("row_sparse indexing supports [:] only")

    def _same_structure_op(self, other, fn):
        if isinstance(other, RowSparseNDArray) \
                and other._shape == self._shape:
            a_idx = self._sp_indices.asnumpy()
            b_idx = other._sp_indices.asnumpy()
            if a_idx.shape == b_idx.shape and (a_idx == b_idx).all():
                c = self._clone()
                c._sp_data = fn(self._sp_data, other._sp_data)
                return c
            import jax.numpy as jnp
            union = _np.union1d(a_idx, b_idx)
            a_pos = _np.searchsorted(union, a_idx)
            b_pos = _np.searchsorted(union, b_idx)
            zero = jnp.zeros((len(union),) + self._shape[1:],
                             dtype=self._sp_data.dtype)
            a_full = zero.at[jnp.asarray(a_pos)].set(self._sp_data._data)
            b_full = zero.at[jnp.asarray(b_pos)].set(other._sp_data._data)
            return RowSparseNDArray(
                fn(NDArray(a_full, ctx=self._ctx),
                   NDArray(b_full, ctx=self._ctx)),
                NDArray(_jnp().asarray(union.astype(_np.int64)),
                        ctx=self._ctx),
                self._shape, ctx=self._ctx)
        return None


# -- constructors (parity: sparse.py:825, 1020) --------------------------

def _as_nd(x, dtype, ctx):
    if isinstance(x, NDArray):
        return x.astype(dtype) if dtype is not None and x.dtype != dtype \
            else x
    return _dense_array(_np.asarray(x, dtype=dtype), ctx=ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray from (data, indices, indptr), a dense
    array/NDArray, a scipy.sparse matrix, or another CSRNDArray."""
    ctx = ctx or current_context()
    try:
        import scipy.sparse as spsp
    except ImportError:
        spsp = None
    if isinstance(arg1, CSRNDArray):
        return arg1._clone(ctx=ctx)
    if spsp is not None and spsp.issparse(arg1):
        m = arg1.tocsr()
        return CSRNDArray(
            _as_nd(m.data, dtype or m.dtype, ctx),
            _as_nd(m.indices.astype(_np.int64), _np.int64, ctx),
            _as_nd(m.indptr.astype(_np.int64), _np.int64, ctx),
            m.shape, ctx=ctx)
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            ind = _np.asarray(indices)
            ip = _np.asarray(indptr)
            shape = (len(ip) - 1,
                     int(ind.max()) + 1 if ind.size else 0)
        return CSRNDArray(_as_nd(data, dtype, ctx),
                          _as_nd(_np.asarray(indices, _np.int64),
                                 _np.int64, ctx),
                          _as_nd(_np.asarray(indptr, _np.int64),
                                 _np.int64, ctx),
                          shape, ctx=ctx)
    if isinstance(arg1, tuple) and len(arg1) == 2:
        if isinstance(arg1[0], int):
            # (M, N) empty
            return zeros("csr", arg1, ctx=ctx, dtype=dtype)
        # (data, (row, col)) COO-style definition
        import scipy.sparse as spsp2
        data, (row, col) = arg1
        m = spsp2.csr_matrix((_np.asarray(data),
                              (_np.asarray(row), _np.asarray(col))),
                             shape=shape)
        return csr_matrix(m, ctx=ctx, dtype=dtype)
    # dense source
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else \
        _np.asarray(arg1, dtype=dtype)
    return cast_storage(_dense_array(src, ctx=ctx), "csr")


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray from (data, indices), a dense source,
    or another RowSparseNDArray."""
    ctx = ctx or current_context()
    if isinstance(arg1, RowSparseNDArray):
        return arg1._clone(ctx=ctx)
    if isinstance(arg1, tuple) and len(arg1) == 2 and not _np.isscalar(
            arg1[0]):
        arr0 = _np.asarray(arg1[0]) if not isinstance(arg1[0], NDArray) \
            else arg1[0]
        if getattr(arr0, "ndim", 0) >= 1 and not isinstance(arg1[0], int):
            data, indices = arg1
            data_nd = _as_nd(data, dtype, ctx)
            if shape is None:
                ind = _np.asarray(indices)
                shape = ((int(ind.max()) + 1 if ind.size else 0),) + \
                    tuple(data_nd.shape[1:])
            return RowSparseNDArray(
                data_nd,
                _as_nd(_np.asarray(indices, _np.int64), _np.int64, ctx),
                shape, ctx=ctx)
    if isinstance(arg1, tuple):
        return zeros("row_sparse", arg1, ctx=ctx, dtype=dtype)
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else \
        _np.asarray(arg1, dtype=dtype)
    return cast_storage(_dense_array(src, ctx=ctx), "row_sparse")


def zeros(stype, shape, ctx=None, dtype=None, **kwargs):
    """All-zero sparse array (reference: sparse.py:1507)."""
    ctx = ctx or current_context()
    dtype = dtype or _np.float32
    if stype == "default":
        return _dense_zeros(shape, ctx=ctx, dtype=dtype)
    if stype == "csr":
        return CSRNDArray(
            _dense_array(_np.zeros((0,), dtype), ctx=ctx),
            _dense_array(_np.zeros((0,), _np.int64), ctx=ctx),
            _dense_array(_np.zeros((shape[0] + 1,), _np.int64), ctx=ctx),
            shape, ctx=ctx)
    if stype == "row_sparse":
        return RowSparseNDArray(
            _dense_array(_np.zeros((0,) + tuple(shape[1:]), dtype),
                         ctx=ctx),
            _dense_array(_np.zeros((0,), _np.int64), ctx=ctx),
            shape, ctx=ctx)
    raise MXNetError("unknown storage type %s" % stype)


def empty(stype, shape, ctx=None, dtype=None):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


def array(source_array, ctx=None, dtype=None):
    """Sparse-aware array constructor (reference: sparse.py:1579)."""
    import scipy.sparse as spsp
    if isinstance(source_array, BaseSparseNDArray):
        return source_array._clone(ctx=ctx or source_array.context)
    if spsp.issparse(source_array):
        return csr_matrix(source_array, ctx=ctx, dtype=dtype)
    raise ValueError("Unexpected source_array type: use mx.nd.array for "
                     "dense sources")


# -- storage casts (parity: cast_storage-inl.h) ---------------------------

def cast_storage(arr, stype):
    """Convert between storage types. Dense→sparse scans for non-zeros
    on the host (nnz is data-dependent; this syncs — same cost class as
    the reference's CPU kernel which also walks the dense array)."""
    if isinstance(arr, BaseSparseNDArray) or stype == "default":
        return arr.tostype(stype)
    if not isinstance(arr, NDArray):
        raise TypeError("cast_storage expects an NDArray")
    if stype == "row_sparse":
        # the row mask is computed on device; only the 1-D bool mask is
        # fetched, and the kept rows are gathered on device — no dense
        # device→host transfer (this runs per-step in sparse_grad
        # training loops)
        import jax.numpy as jnp
        g = arr._data
        mask = jnp.any(g != 0, axis=tuple(range(1, g.ndim))) \
            if g.ndim > 1 else (g != 0)
        nz_rows = _np.where(_np.asarray(mask))[0].astype(_np.int64)
        data = jnp.take(g, jnp.asarray(nz_rows), axis=0) if nz_rows.size \
            else jnp.zeros((0,) + tuple(arr.shape[1:]), dtype=g.dtype)
        return RowSparseNDArray(
            NDArray(data, ctx=arr.context),
            _dense_array(nz_rows, ctx=arr.context),
            arr.shape, ctx=arr.context)
    if stype == "csr":
        import scipy.sparse as spsp
        src = arr.asnumpy()
        if src.ndim != 2:
            raise MXNetError("csr requires 2-D input")
        m = spsp.csr_matrix(src)
        return CSRNDArray(
            _dense_array(m.data.astype(src.dtype), ctx=arr.context),
            _dense_array(m.indices.astype(_np.int64), ctx=arr.context),
            _dense_array(m.indptr.astype(_np.int64), ctx=arr.context),
            src.shape, ctx=arr.context)
    raise MXNetError("unknown storage type %s" % stype)


def retain(rsp, indices):
    """Keep only the requested rows of a row_sparse array (reference:
    _retain op) — a gather over the stored rows."""
    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    want = indices.asnumpy().astype(_np.int64) \
        if isinstance(indices, NDArray) else \
        _np.asarray(indices, dtype=_np.int64)
    have = rsp.indices.asnumpy()
    mask = _np.isin(want, have)
    kept = want[mask]
    pos = _np.searchsorted(have, kept)
    import jax.numpy as jnp
    data = jnp.take(rsp.data._data, jnp.asarray(pos), axis=0) \
        if kept.size else \
        jnp.zeros((0,) + rsp.shape[1:], dtype=rsp.data.dtype)
    return RowSparseNDArray(
        NDArray(data, ctx=rsp.context),
        _dense_array(kept, ctx=rsp.context),
        rsp.shape, ctx=rsp.context)


# -- sparse dot (parity: src/operator/tensor/dot-inl.h) -------------------

def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot. csr×dense lowers to gather + segment_sum (the
    MXU-friendly formulation); everything else falls back to dense."""
    import jax
    import jax.numpy as jnp
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray) \
            and not isinstance(rhs, BaseSparseNDArray) and not transpose_b:
        data = lhs.data._data
        cols = lhs.indices._data
        rows = jnp.asarray(lhs._row_ids())
        vec = rhs.ndim == 1  # matrix-vector (reference DotCsrDnsDns)
        if not transpose_a:
            # (M,K)·(K,N): each nnz contributes data*rhs[col] to its row
            taken = jnp.take(rhs._data, cols, axis=0)
            contrib = data * taken if vec else data[:, None] * taken
            out = jax.ops.segment_sum(contrib, rows,
                                      num_segments=lhs.shape[0])
        else:
            # (M,K)ᵀ·(M,N) → (K,N): contributes data*rhs[row] to its col
            taken = jnp.take(rhs._data, rows, axis=0)
            contrib = data * taken if vec else data[:, None] * taken
            out = jax.ops.segment_sum(contrib, cols,
                                      num_segments=lhs.shape[1])
        return NDArray(out, ctx=lhs.context)
    a = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) \
        else lhs
    b = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) \
        else rhs
    return a.dot(b, transpose_a=transpose_a, transpose_b=transpose_b)


# -- elemwise wrappers (parity: sparse.py:1193-1504) ----------------------

def add(lhs, rhs):
    return lhs + rhs


def subtract(lhs, rhs):
    return lhs - rhs


def multiply(lhs, rhs):
    return lhs * rhs


def divide(lhs, rhs):
    return lhs / rhs
