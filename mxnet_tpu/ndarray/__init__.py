"""NDArray namespace: the imperative API surface (``mx.nd``).

Op functions are code-generated from the registry at import time,
mirroring python/mxnet/ndarray/register.py in the reference.
"""
from .ndarray import (NDArray, invoke_nd, array, zeros, ones, full, empty,
                      arange, linspace, eye, moveaxis, concatenate, save,
                      load, waitall, add, subtract, multiply, divide, modulo,
                      power, maximum, minimum, hypot, equal, not_equal,
                      greater, greater_equal, lesser, lesser_equal,
                      logical_and, logical_or, logical_xor, true_divide)
from . import random
from .register import install_ops as _install_ops

_install_ops(globals())

# `op` alias module-like access (mx.nd.op.FullyConnected)
import types as _types

op = _types.ModuleType(__name__ + ".op")
_install_ops(op.__dict__)

from . import sparse
from .sparse import (BaseSparseNDArray, CSRNDArray, RowSparseNDArray,
                     csr_matrix, row_sparse_array, cast_storage, retain)

# sparse-aware dot: csr/row_sparse operands dispatch to the gather/
# segment-sum lowering (the reference's FComputeEx storage dispatch,
# src/operator/tensor/dot-inl.h)
_dense_dot = globals().get("dot")


def dot(lhs, rhs, transpose_a=False, transpose_b=False, out=None,
        **kwargs):
    if isinstance(lhs, BaseSparseNDArray) \
            or isinstance(rhs, BaseSparseNDArray):
        res = sparse.dot(lhs, rhs, transpose_a=transpose_a,
                         transpose_b=transpose_b)
        if out is not None:
            out._set_data(res._data)
            return out
        return res
    return _dense_dot(lhs, rhs, transpose_a=transpose_a,
                      transpose_b=transpose_b, out=out, **kwargs)


op.dot = dot

from . import contrib  # noqa: F401  (foreach/while_loop/cond)
