"""NDArray namespace: the imperative API surface (``mx.nd``).

Op functions are code-generated from the registry at import time,
mirroring python/mxnet/ndarray/register.py in the reference.
"""
from .ndarray import (NDArray, invoke_nd, array, zeros, ones, full, empty,
                      arange, linspace, eye, moveaxis, concatenate, save,
                      load, waitall, add, subtract, multiply, divide, modulo,
                      power, maximum, minimum, hypot, equal, not_equal,
                      greater, greater_equal, lesser, lesser_equal,
                      logical_and, logical_or, logical_xor, true_divide)
from . import random
from .register import install_ops as _install_ops

_install_ops(globals())

# `op` alias module-like access (mx.nd.op.FullyConnected)
import types as _types

op = _types.ModuleType(__name__ + ".op")
_install_ops(op.__dict__)

# sparse is populated by the sparse module when imported
