"""NDArray — the user-visible array type.

Parity target: python/mxnet/ndarray/ndarray.py + src/ndarray/ndarray.cc.

TPU-native design: an :class:`NDArray` is a *mutable handle* over an
immutable ``jax.Array`` buffer. The reference's in-place semantics
(``x[:] = v``, ``kvstore.pull(out=w)``, optimizer updates) become buffer
swaps on the handle; aliasing views are not shared (documented
divergence — XLA owns memory layout). Asynchrony comes from JAX's async
dispatch: every op returns immediately with a future-backed array, and
``wait_to_read`` is ``block_until_ready`` — this replaces the reference's
dependency-engine Var scheduling (SURVEY §7: ThreadedEngine row).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError, numeric_types, integer_types
from ..context import Context, current_context
from .. import ops as _ops

__all__ = ["NDArray", "invoke_nd", "array", "zeros", "ones", "full", "empty",
           "arange", "linspace", "eye", "moveaxis", "concatenate", "save",
           "load", "waitall", "imperative_mixed_precision"]


def _dtype_np(dt):
    return _np.dtype(dt) if dt is not None else None


class NDArray:
    """Multi-dimensional array on a device, with async semantics."""

    __array_priority__ = 1000.0

    def __init__(self, data, ctx=None):
        self._data = data          # jax.Array
        self._ctx = ctx if ctx is not None else current_context()
        self.grad = None           # NDArray or None
        self._grad_req = "null"
        self._tape_node = None     # autograd record entry
        self._tape_index = 0
        self._fresh_grad = False

    # -- basic properties ------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def size(self):
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def T(self):
        return self.transpose()

    @property
    def handle(self):
        # parity shim: reference exposes the C handle; we expose jax.Array
        return self._data

    # -- sync / host transfer -------------------------------------------
    def wait_to_read(self):
        self._data.block_until_ready()

    def wait_to_write(self):
        self._data.block_until_ready()

    def asnumpy(self):
        return _np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("The truth value of an NDArray with multiple "
                         "elements is ambiguous.")

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        return '\n%s\n<NDArray %s @%s>' % (
            str(self.asnumpy()), 'x'.join(str(s) for s in self.shape),
            self._ctx)

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    # -- conversion ------------------------------------------------------
    def astype(self, dtype, copy=True):
        dt = _np.dtype(dtype)
        if not copy and self.dtype == dt:
            return self
        return invoke_nd("Cast", [self], {"dtype": dt.name})

    def copy(self):
        return invoke_nd("_copy", [self], {})

    def copyto(self, other):
        if isinstance(other, NDArray):
            if other is self:
                return other
            other._set_data(_device_put(self._data, other._ctx))
            return other
        if isinstance(other, Context):
            out = NDArray(_device_put(self._data, other), ctx=other)
            return out
        raise TypeError("copyto does not support type " + str(type(other)))

    def as_in_context(self, context):
        if self._ctx == context:
            return self
        return self.copyto(context)

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sp
        return _sp.cast_storage(self, stype)

    def to_dlpack_for_read(self):
        from jax import dlpack as _dl
        return _dl.to_dlpack(self._data)

    # -- mutation (handle swap) -----------------------------------------
    def _set_data(self, new_data):
        self._data = new_data

    def __setitem__(self, key, value):
        import jax.numpy as jnp
        key = _clean_index(key)
        if isinstance(value, NDArray):
            v = value._data
        elif isinstance(value, numeric_types):
            v = value
        else:
            v = jnp.asarray(_np.asarray(value), dtype=self._data.dtype)
        if key == slice(None) and not isinstance(v, (int, float)) \
                and getattr(v, "shape", None) == self.shape:
            self._set_data(jnp.asarray(v, dtype=self._data.dtype))
        else:
            self._set_data(self._data.at[key].set(v))

    def __getitem__(self, key):
        # Routed through the registered `_getitem` op so the lookup is
        # recorded on the autograd tape (gradients flow through any
        # slice/int/fancy index, as in the reference which lowers
        # indexing to op.slice/op.take/op.gather_nd).
        spec, arrays = _index_spec(key, self._ctx)
        return invoke_nd("_getitem", [self] + arrays,
                         {"spec": spec, "num_arrays": len(arrays)})

    # -- autograd --------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        from .. import autograd  # noqa: F401
        self.grad = zeros(self.shape, ctx=self._ctx, dtype=self.dtype)
        self._grad_req = grad_req
        self._fresh_grad = False

    def detach(self):
        out = NDArray(self._data, ctx=self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self],
                          None if out_grad is None else [out_grad],
                          retain_graph=retain_graph, train_mode=train_mode)

    # -- generic op access ----------------------------------------------
    def _op1(self, opname, **kwargs):
        return invoke_nd(opname, [self], kwargs)

    # named math methods (subset of the reference's generated methods)
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if not shape:
            shape = kwargs.get("shape", None)
        reverse = kwargs.get("reverse", False)
        return invoke_nd("Reshape", [self],
                         {"shape": tuple(shape), "reverse": reverse})

    def reshape_like(self, other):
        return invoke_nd("reshape_like", [self, other], {})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return invoke_nd("transpose", [self], {"axes": axes or None})

    def swapaxes(self, dim1, dim2):
        return invoke_nd("SwapAxis", [self], {"dim1": dim1, "dim2": dim2})

    def flatten(self):
        return invoke_nd("Flatten", [self], {})

    def expand_dims(self, axis):
        return invoke_nd("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return invoke_nd("squeeze", [self], {"axis": axis})

    def broadcast_to(self, shape):
        return invoke_nd("broadcast_to", [self], {"shape": tuple(shape)})

    def broadcast_like(self, other):
        return invoke_nd("broadcast_like", [self, other], {})

    def tile(self, reps):
        return invoke_nd("tile", [self], {"reps": tuple(reps)})

    def repeat(self, repeats, axis=None):
        return invoke_nd("repeat", [self], {"repeats": repeats, "axis": axis})

    def pad(self, mode, pad_width, constant_value=0.0):
        return invoke_nd("Pad", [self], {"mode": mode, "pad_width": pad_width,
                                         "constant_value": constant_value})

    def flip(self, axis):
        return invoke_nd("reverse", [self], {"axis": axis})

    def clip(self, a_min, a_max):
        return invoke_nd("clip", [self], {"a_min": a_min, "a_max": a_max})

    def slice(self, begin, end, step=None):
        return invoke_nd("slice", [self],
                         {"begin": begin, "end": end, "step": step})

    def slice_axis(self, axis, begin, end):
        return invoke_nd("slice_axis", [self],
                         {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return invoke_nd("take", [self, _as_nd(indices, self._ctx)],
                         {"axis": axis, "mode": mode})

    def one_hot(self, depth, **kwargs):
        return invoke_nd("one_hot", [self], dict(kwargs, depth=depth))

    def pick(self, index, axis=-1, keepdims=False):
        return invoke_nd("pick", [self, _as_nd(index, self._ctx)],
                         {"axis": axis, "keepdims": keepdims})

    def sort(self, axis=-1, is_ascend=True):
        return invoke_nd("sort", [self], {"axis": axis, "is_ascend": is_ascend})

    def argsort(self, axis=-1, is_ascend=True):
        return invoke_nd("argsort", [self],
                         {"axis": axis, "is_ascend": is_ascend})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return invoke_nd("topk", [self], {"axis": axis, "k": k,
                                          "ret_typ": ret_typ,
                                          "is_ascend": is_ascend})

    def dot(self, other, transpose_a=False, transpose_b=False):
        return invoke_nd("dot", [self, other],
                         {"transpose_a": transpose_a,
                          "transpose_b": transpose_b})

    # reductions
    def sum(self, axis=None, keepdims=False, **kw):
        return invoke_nd("sum", [self], {"axis": axis, "keepdims": keepdims})

    def nansum(self, axis=None, keepdims=False, **kw):
        return invoke_nd("nansum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False, **kw):
        return invoke_nd("mean", [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False, **kw):
        return invoke_nd("prod", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False, **kw):
        return invoke_nd("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False, **kw):
        return invoke_nd("min", [self], {"axis": axis, "keepdims": keepdims})

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke_nd("norm", [self],
                         {"ord": ord, "axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return invoke_nd("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return invoke_nd("argmin", [self], {"axis": axis, "keepdims": keepdims})

    # unary math (generated-method parity via explicit list)
    def abs(self):
        return self._op1("abs")

    def sign(self):
        return self._op1("sign")

    def sqrt(self):
        return self._op1("sqrt")

    def square(self):
        return self._op1("square")

    def exp(self):
        return self._op1("exp")

    def log(self):
        return self._op1("log")

    def sigmoid(self):
        return self._op1("sigmoid")

    def tanh(self):
        return self._op1("tanh")

    def relu(self):
        return self._op1("relu")

    def softmax(self, axis=-1):
        return invoke_nd("softmax", [self], {"axis": axis})

    def log_softmax(self, axis=-1):
        return invoke_nd("log_softmax", [self], {"axis": axis})

    def round(self):
        return self._op1("round")

    def floor(self):
        return self._op1("floor")

    def ceil(self):
        return self._op1("ceil")

    def zeros_like(self):
        return self._op1("zeros_like")

    def ones_like(self):
        return self._op1("ones_like")

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke_nd("SliceChannel", [self],
                         {"num_outputs": num_outputs, "axis": axis,
                          "squeeze_axis": squeeze_axis})

    # -- arithmetic operators -------------------------------------------
    def _binary(self, other, op, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            args = [other, self] if reverse else [self, other]
            return invoke_nd(op, args, {})
        if isinstance(other, numeric_types):
            sname = scalar_op if not reverse else _RSCALAR.get(
                scalar_op, scalar_op)
            return invoke_nd(sname, [self], {"scalar": other})
        if isinstance(other, _np.ndarray):
            return self._binary(array(other, ctx=self._ctx), op, scalar_op,
                                reverse)
        raise TypeError("type %s not supported" % str(type(other)))

    def __add__(self, other):
        return self._binary(other, "broadcast_add", "_plus_scalar")

    def __radd__(self, other):
        return self.__add__(other)

    def __iadd__(self, other):
        out = self.__add__(other)
        self._set_data(out._data)
        return self

    def __sub__(self, other):
        return self._binary(other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return self._binary(other, "broadcast_sub", "_minus_scalar",
                            reverse=True)

    def __isub__(self, other):
        out = self.__sub__(other)
        self._set_data(out._data)
        return self

    def __mul__(self, other):
        return self._binary(other, "broadcast_mul", "_mul_scalar")

    def __rmul__(self, other):
        return self.__mul__(other)

    def __imul__(self, other):
        out = self.__mul__(other)
        self._set_data(out._data)
        return self

    def __truediv__(self, other):
        return self._binary(other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return self._binary(other, "broadcast_div", "_div_scalar",
                            reverse=True)

    def __itruediv__(self, other):
        out = self.__truediv__(other)
        self._set_data(out._data)
        return self

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __mod__(self, other):
        return self._binary(other, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, other):
        return self._binary(other, "broadcast_mod", "_mod_scalar",
                            reverse=True)

    def __pow__(self, other):
        return self._binary(other, "broadcast_power", "_power_scalar")

    def __rpow__(self, other):
        return self._binary(other, "broadcast_power", "_power_scalar",
                            reverse=True)

    def __matmul__(self, other):
        return self.dot(other)

    def __neg__(self):
        return invoke_nd("negative", [self], {})

    def __abs__(self):
        return invoke_nd("abs", [self], {})

    def __eq__(self, other):
        if other is None:
            return False
        return self._binary(other, "broadcast_equal", "_equal_scalar")

    def __ne__(self, other):
        if other is None:
            return True
        return self._binary(other, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, other):
        return self._binary(other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._binary(other, "broadcast_greater_equal",
                            "_greater_equal_scalar")

    def __lt__(self, other):
        return self._binary(other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._binary(other, "broadcast_lesser_equal",
                            "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __getstate__(self):
        return {"data": self.asnumpy(), "ctx": str(self._ctx)}

    def __setstate__(self, state):
        import jax.numpy as jnp
        self._data = jnp.asarray(state["data"])
        self._ctx = current_context()
        self.grad = None
        self._grad_req = "null"
        self._tape_node = None
        self._tape_index = 0
        self._fresh_grad = False


_RSCALAR = {"_minus_scalar": "_rminus_scalar", "_div_scalar": "_rdiv_scalar",
            "_mod_scalar": "_rmod_scalar", "_power_scalar": "_rpower_scalar"}


def _clean_index(key):
    """Convert NDArray indices inside a key to numpy/int."""
    if isinstance(key, NDArray):
        return key.asnumpy().astype(_np.int32)
    if isinstance(key, tuple):
        return tuple(_clean_index(k) for k in key)
    return key


def _index_spec(key, ctx):
    """Normalize an indexing key into (hashable spec, array inputs).

    Spec item kinds: ("s", start, stop, step) slice, ("b", v) bool
    scalar, ("n",) newaxis, ("e",) ellipsis, ("a",) array placeholder
    consumed in order from the extra op inputs (integers become 0-d
    array inputs so distinct values share one compiled program).
    Boolean masks are converted to integer coordinate arrays host-side
    (they are concrete values in the eager path, so this costs one sync
    at most).
    """
    items = key if isinstance(key, tuple) else (key,)
    spec = []
    arrays = []

    def push_array(a):
        np_a = a.asnumpy() if isinstance(a, NDArray) else _np.asarray(a)
        if np_a.dtype == _np.bool_:
            for coord in _np.nonzero(np_a):
                spec.append(("a",))
                arrays.append(array(coord.astype(_np.int32), ctx=ctx))
        else:
            spec.append(("a",))
            if isinstance(a, NDArray) and np_a.dtype != _np.bool_:
                arrays.append(a)
            else:
                arrays.append(array(np_a.astype(_np.int32), ctx=ctx))

    for it in items:
        if isinstance(it, slice):
            spec.append(("s", it.start, it.stop, it.step))
        elif it is None:
            spec.append(("n",))
        elif it is Ellipsis:
            spec.append(("e",))
        elif isinstance(it, (bool, _np.bool_)):
            # bool scalars are 0-d masks (numpy semantics: insert an
            # axis of size int(v)), NOT integers — and bool is an int
            # subclass, so this must be checked first.
            spec.append(("b", bool(it)))
        elif isinstance(it, integer_types) or isinstance(it, _np.integer):
            # pass the value as a 0-d array input, not a baked attr, so
            # x[0], x[1], ... share ONE compiled program (ints among
            # advanced indices are 0-d advanced indices in numpy, so
            # semantics are unchanged; jnp wraps negative values).
            spec.append(("a",))
            arrays.append(array(_np.int32(int(it)), ctx=ctx))
        elif isinstance(it, (NDArray, _np.ndarray, list)):
            push_array(it)
        else:
            raise MXNetError("NDArray indexing does not support key "
                             "component of type %s" % type(it))
    return tuple(spec), arrays


def _as_nd(x, ctx=None):
    if isinstance(x, NDArray):
        return x
    return array(x, ctx=ctx)


def _device_put(data, ctx: Context):
    import jax
    try:
        return jax.device_put(data, ctx.jax_device())
    except Exception:
        return data


# ---------------------------------------------------------------------------
# The imperative entry point (Imperative::Invoke analogue)
# ---------------------------------------------------------------------------

def invoke_nd(op_name, inputs, attrs, out=None, ctx=None):
    """Eagerly invoke a registered op on NDArrays.

    Mirrors MXImperativeInvokeEx → Imperative::Invoke
    (reference: src/c_api/c_api_ndarray.cc:132, imperative.cc:87).
    """
    from .. import autograd
    from .. import random as _random

    op = _ops.get_op(op_name) if isinstance(op_name, str) else op_name
    attrs = {k: v for k, v in attrs.items() if v is not None or k in ("axis",)}
    if "__train__" in op.defaults:
        attrs["__train__"] = autograd.is_training()

    rng = None
    if op.needs_rng:
        rng = _random.new_key()

    raw = [i._data for i in inputs]
    outputs, aux_updates = _ops.invoke(op, raw, attrs, rng=rng)

    octx = ctx or (inputs[0]._ctx if inputs else current_context())
    if not inputs:
        # nullary op: honor ctx placement
        if isinstance(octx, str):
            octx = Context(octx.split("(")[0], 0)
        outputs = tuple(_device_put(o, octx) for o in outputs)

    out_nds = [NDArray(o, ctx=octx) for o in outputs]

    # aux writeback (BatchNorm moving stats, optimizer states)
    for idx, val in aux_updates:
        inputs[idx]._set_data(val)

    if autograd.is_recording():
        autograd._record_op(op, _ops.normalize_attrs(op, attrs), inputs,
                            out_nds, rng)

    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o, nd in zip(outs, out_nds):
            o._set_data(nd._data)
            o._tape_node = nd._tape_node
            o._tape_index = nd._tape_index
        return out

    if len(out_nds) == 1:
        return out_nds[0]
    return out_nds


# ---------------------------------------------------------------------------
# Creation functions
# ---------------------------------------------------------------------------

def array(source_array, ctx=None, dtype=None):
    import jax.numpy as jnp
    ctx = ctx or current_context()
    was_np = isinstance(source_array, (_np.ndarray, _np.generic, NDArray)) \
        or hasattr(source_array, "__jax_array__") \
        or type(source_array).__module__.startswith("jax")
    if isinstance(source_array, NDArray):
        src = source_array.asnumpy()
    else:
        src = _np.asarray(source_array)
    from ..util import canonical_dtype
    if dtype is None:
        # MXNet: python lists default to float32; numpy keeps its dtype.
        # float64 always demotes to float32 (TPU-native math width);
        # int64 demotes unless MXNET_INT64_TENSOR_SIZE enables x64
        # (large-tensor index support, ref USE_INT64_TENSOR_SIZE).
        if not was_np or src.dtype == _np.float64:
            dtype = _np.float32
        else:
            dtype = canonical_dtype(src.dtype)
    # canonical_dtype demotes EXPLICITLY so jax never emits its
    # implicit-truncation warning (VERDICT r4 item 5)
    data = jnp.asarray(src, dtype=canonical_dtype(dtype))
    return NDArray(_device_put(data, ctx), ctx=ctx)


def zeros(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, integer_types):
        shape = (shape,)
    return invoke_nd("_zeros", [], {"shape": tuple(shape),
                                    "dtype": _np.dtype(dtype or "float32").name},
                     ctx=ctx or current_context())


def ones(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, integer_types):
        shape = (shape,)
    return invoke_nd("_ones", [], {"shape": tuple(shape),
                                   "dtype": _np.dtype(dtype or "float32").name},
                     ctx=ctx or current_context())


def full(shape, val, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, integer_types):
        shape = (shape,)
    return invoke_nd("_full", [], {"shape": tuple(shape), "value": val,
                                   "dtype": _np.dtype(dtype or "float32").name},
                     ctx=ctx or current_context())


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    return invoke_nd("_arange", [],
                     {"start": start, "stop": stop, "step": step,
                      "repeat": repeat, "dtype": _np.dtype(dtype).name},
                     ctx=ctx or current_context())


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    return invoke_nd("_linspace", [],
                     {"start": start, "stop": stop, "num": num,
                      "endpoint": endpoint, "dtype": _np.dtype(dtype).name},
                     ctx=ctx or current_context())


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    return invoke_nd("_eye", [], {"N": N, "M": M, "k": k,
                                  "dtype": _np.dtype(dtype).name},
                     ctx=ctx or current_context())


def moveaxis(tensor, source, destination):
    axes = list(range(tensor.ndim))
    try:
        source = [source] if isinstance(source, int) else list(source)
        destination = [destination] if isinstance(destination, int) \
            else list(destination)
    except TypeError:
        raise MXNetError("bad source/destination")
    for s in source:
        axes.remove(s % tensor.ndim)
    for d, s in sorted(zip(destination, source)):
        axes.insert(d % tensor.ndim, s % tensor.ndim)
    return tensor.transpose(axes)


def concatenate(arrays, axis=0, always_copy=True):
    return invoke_nd("Concat", list(arrays),
                     {"dim": axis, "num_args": len(arrays)})


# module-level binary helpers (parity: ndarray.py maximum/minimum/...)
def _ufunc(lhs, rhs, op, scalar_op, rscalar_op=None):
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return invoke_nd(op, [lhs, rhs], {})
    if isinstance(lhs, NDArray):
        return invoke_nd(scalar_op, [lhs], {"scalar": rhs})
    if isinstance(rhs, NDArray):
        return invoke_nd(rscalar_op or scalar_op, [rhs], {"scalar": lhs})
    raise TypeError("at least one argument must be an NDArray")


def add(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_add", "_plus_scalar")


def subtract(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_sub", "_minus_scalar",
                  "_rminus_scalar")


def multiply(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_mul", "_mul_scalar")


def divide(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_div", "_div_scalar", "_rdiv_scalar")


def modulo(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_mod", "_mod_scalar", "_rmod_scalar")


def power(base, exp):
    return _ufunc(base, exp, "broadcast_power", "_power_scalar",
                  "_rpower_scalar")


def maximum(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_maximum", "_maximum_scalar")


def minimum(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_minimum", "_minimum_scalar")


def hypot(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_hypot", "_hypot_scalar")


def equal(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_equal", "_equal_scalar")


def not_equal(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_not_equal", "_not_equal_scalar")


def greater(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_greater", "_greater_scalar")


def greater_equal(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_greater_equal",
                  "_greater_equal_scalar")


def lesser(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_lesser", "_lesser_scalar")


def lesser_equal(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_lesser_equal", "_lesser_equal_scalar")


def logical_and(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_logical_and", "_logical_and_scalar")


def logical_or(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_logical_or", "_logical_or_scalar")


def logical_xor(lhs, rhs):
    return _ufunc(lhs, rhs, "broadcast_logical_xor", "_logical_xor_scalar")


def true_divide(lhs, rhs):
    return divide(lhs, rhs)


def waitall():
    import jax
    try:
        jax.effects_barrier()
    except Exception:
        pass


def imperative_mixed_precision(enable=True):
    """Placeholder for AMP hooks (contrib/amp in later reference versions)."""


# ---------------------------------------------------------------------------
# Serialization (reference: src/ndarray/ndarray.cc Save/Load, magic
# 0xF993fac9; here an npz container with the same list/dict surface)
# ---------------------------------------------------------------------------

_SAVE_LIST_KEY = "__mxnet_tpu_list__"


# sparse-aware serialization (the reference NDArray::Save is magic-
# tagged and sparse-aware, ndarray.cc:1576): sparse entries spill their
# components under reserved key prefixes inside the same npz payload
_SP_CSR_KEY = "__sparse_csr__::"
_SP_RSP_KEY = "__sparse_rsp__::"


def _flatten_entry(key, val, arrays):
    from .sparse import CSRNDArray, RowSparseNDArray
    if isinstance(val, CSRNDArray):
        p = _SP_CSR_KEY + key + "::"
        arrays[p + "data"] = val.data.asnumpy()
        arrays[p + "indices"] = val.indices.asnumpy()
        arrays[p + "indptr"] = val.indptr.asnumpy()
        arrays[p + "shape"] = _np.asarray(val.shape, _np.int64)
    elif isinstance(val, RowSparseNDArray):
        p = _SP_RSP_KEY + key + "::"
        arrays[p + "data"] = val.data.asnumpy()
        arrays[p + "indices"] = val.indices.asnumpy()
        arrays[p + "shape"] = _np.asarray(val.shape, _np.int64)
    else:
        arrays[key] = val.asnumpy()


def save(fname, data):
    if isinstance(data, NDArray) or (
            hasattr(data, "stype") and hasattr(data, "asnumpy")):
        data = [data]
    arrays = {}
    if isinstance(data, dict):
        for k, v in data.items():
            _flatten_entry(k, v, arrays)
    elif isinstance(data, (list, tuple)):
        for i, v in enumerate(data):
            _flatten_entry("%s%d" % (_SAVE_LIST_KEY, i), v, arrays)
    else:
        raise ValueError("data needs to either be a NDArray, dict of (str, "
                         "NDArray) pairs or a list of NDarrays.")
    # write-then-rename: a preempted save can never leave a truncated
    # file at fname (the file object keeps numpy from appending .npz)
    import os
    tmp = fname + ".tmp"
    with open(tmp, "wb") as sink:
        _np.savez(sink, **arrays)
    os.replace(tmp, fname)


def _unflatten(loaded):
    from .sparse import CSRNDArray, RowSparseNDArray
    out = {}
    sparse_parts = {}
    for k in loaded.keys():
        for prefix, stype in ((_SP_CSR_KEY, "csr"),
                              (_SP_RSP_KEY, "row_sparse")):
            if k.startswith(prefix):
                name, part = k[len(prefix):].rsplit("::", 1)
                sparse_parts.setdefault((name, stype), {})[part] = \
                    loaded[k]
                break
        else:
            out[k] = array(loaded[k])
    for (name, stype), parts in sparse_parts.items():
        shape = tuple(int(s) for s in parts["shape"])
        if stype == "csr":
            out[name] = CSRNDArray(
                array(parts["data"]), array(parts["indices"]),
                array(parts["indptr"]), shape)
        else:
            out[name] = RowSparseNDArray(
                array(parts["data"]), array(parts["indices"]), shape)
    return out


def load(fname):
    with open(fname, "rb") as f:
        loaded = _np.load(f, allow_pickle=False)
        out = _unflatten(loaded)
        keys = list(out.keys())
        if keys and all(k.startswith(_SAVE_LIST_KEY) for k in keys):
            return [out["%s%d" % (_SAVE_LIST_KEY, i)]
                    for i in range(len(keys))]
        return out
