"""Imperative control flow (reference: python/mxnet/ndarray/contrib.py
foreach/while_loop/cond).

Eager semantics: the loop runs on the host, each iteration's ops are
recorded on the autograd tape, so gradients flow with no extra
machinery (the reference builds a subgraph op even eagerly; we match
its *semantics* — for the compiled/XLA-native path use the symbolic
`sym.contrib.foreach` & co., or hybridize, which lower to one
``lax.scan``).

Divergence (documented): ``while_loop`` zero-fills the rows of the
stacked outputs beyond the executed step count; the reference leaves
them undefined.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["foreach", "while_loop", "cond"]


def _as_list(x):
    if x is None:
        return [], True
    if isinstance(x, (list, tuple)):
        return list(x), False
    return [x], True


def foreach(body, data, init_states):
    """Run ``body`` over dim 0 of ``data``; body(data_item, states) ->
    (outputs, new_states). Returns (stacked_outputs, final_states)."""
    from . import stack as _stack
    data_list, data_single = _as_list(data)
    states, states_single = _as_list(init_states)
    if not data_list:
        raise MXNetError("foreach needs at least one data input")
    length = data_list[0].shape[0]
    for d in data_list[1:]:
        if d.shape[0] != length:
            raise MXNetError("foreach data inputs disagree on dim 0")

    collected = None
    outs_single = True
    for i in range(length):
        eles = [d[i] for d in data_list]
        outs, states = body(eles[0] if data_single else eles,
                            states[0] if states_single else list(states))
        outs, outs_single = _as_list(outs)
        states, _ = _as_list(states)
        if collected is None:
            collected = [[] for _ in outs]
        for slot, o in zip(collected, outs):
            slot.append(o)
    stacked = [_stack(*slot, axis=0) for slot in (collected or [])]
    return (stacked[0] if outs_single and stacked else stacked,
            states[0] if states_single else states)


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Run ``func`` while ``cond`` holds, at most ``max_iterations``
    times; cond(*loop_vars) -> scalar, func(*loop_vars) -> (outputs,
    new_loop_vars). Stacked outputs have max_iterations rows (tail
    zero-filled); also returns the final loop_vars."""
    from . import stack as _stack, zeros_like as _zeros_like
    loop_vars, single_var = _as_list(loop_vars)
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    if not loop_vars:
        raise MXNetError("while_loop requires at least one loop var")

    collected = None
    outs_single = True
    steps = 0
    while steps < int(max_iterations) and \
            bool(cond(*loop_vars).asnumpy().reshape(())):
        step = func(*loop_vars)
        if not (isinstance(step, tuple) and len(step) == 2):
            raise MXNetError(
                "while_loop func must return (outputs, new_loop_vars)")
        outs, new_vars = step
        outs, outs_single = _as_list(outs)
        new_vars, _ = _as_list(new_vars)
        if len(new_vars) != len(loop_vars):
            raise MXNetError(
                "while_loop func returned %d loop_vars, expected %d"
                % (len(new_vars), len(loop_vars)))
        loop_vars = new_vars
        if collected is None:
            collected = [[] for _ in outs]
        for slot, o in zip(collected, outs):
            slot.append(o)
        steps += 1

    if collected is None:
        raise MXNetError(
            "while_loop executed zero steps; cannot infer output shapes "
            "(the reference raises here too)")
    stacked = []
    for slot in collected:
        pad = [_zeros_like(slot[0])] * (int(max_iterations) - len(slot))
        stacked.append(_stack(*(slot + pad), axis=0))
    return (stacked[0] if outs_single else stacked,
            loop_vars[0] if single_var else loop_vars)


def cond(pred, then_func, else_func):
    """Run one branch based on scalar ``pred`` (an NDArray); the branch
    functions take no arguments (they close over outer NDArrays)."""
    taken = bool(pred.asnumpy().reshape(()))
    return then_func() if taken else else_func()


# -- DGL graph sampling (user-facing CSR API over the lowered ops) --------

def _csr_pieces(csr):
    return [csr.indptr._data, csr.indices._data, csr.data._data]


def _mk_csr(indptr, cols, eids, shape, ctx):
    from .sparse import CSRNDArray
    from .ndarray import NDArray
    import jax.numpy as jnp
    return CSRNDArray(NDArray(jnp.asarray(eids)),
                      NDArray(jnp.asarray(cols)),
                      NDArray(jnp.asarray(indptr)), shape, ctx=ctx)


def _dgl_sample(csr, seeds, uniform, probability=None, num_hops=1,
                num_neighbor=2, max_num_vertices=100):
    """Shared body of the two neighbor-sampling wrappers (reference
    output grouping: all vertex arrays, then all sub-CSRs, then all
    layer arrays; non-uniform inserts per-vertex probabilities after
    the vertex group, dgl_graph.cc:758/852)."""
    from .. import ops as _ops
    from .ndarray import NDArray
    import jax.numpy as jnp
    seeds = seeds if isinstance(seeds, (list, tuple)) else [seeds]
    n = len(seeds)
    name = "_contrib_dgl_csr_neighbor_uniform_sample" if uniform \
        else "_contrib_dgl_csr_neighbor_non_uniform_sample"
    op = _ops.get_op(name)
    raw = _csr_pieces(csr) + [s._data for s in seeds]
    base = 3
    if not uniform:
        raw = [probability._data] + raw
        base = 4
    attrs = {"num_args": base + n, "num_hops": num_hops,
             "num_neighbor": num_neighbor,
             "max_num_vertices": max_num_vertices}
    outs, _ = _ops.invoke(op, raw, attrs)
    per = 5 if uniform else 6
    verts, probs, csrs, layers = [], [], [], []
    max_v = int(max_num_vertices)
    for i in range(n):
        chunk = outs[per * i: per * (i + 1)]
        it = iter(chunk)
        verts.append(NDArray(jnp.asarray(next(it))))
        if not uniform:
            probs.append(NDArray(jnp.asarray(next(it))))
        layer = jnp.asarray(next(it))
        indptr, cols, eids = (next(it), next(it), next(it))
        csrs.append(_mk_csr(indptr, cols, eids,
                            (max_v, csr.shape[1]), csr.context))
        layers.append(NDArray(layer))
    out = verts + (probs if not uniform else []) + csrs + layers
    return out if len(out) > 1 else out[0]


def dgl_csr_neighbor_uniform_sample(csr, seeds, num_hops=1,
                                    num_neighbor=2,
                                    max_num_vertices=100):
    return _dgl_sample(csr, seeds, True, num_hops=num_hops,
                       num_neighbor=num_neighbor,
                       max_num_vertices=max_num_vertices)


def dgl_csr_neighbor_non_uniform_sample(csr, probability, seeds,
                                        num_hops=1, num_neighbor=2,
                                        max_num_vertices=100):
    return _dgl_sample(csr, seeds, False, probability=probability,
                       num_hops=num_hops, num_neighbor=num_neighbor,
                       max_num_vertices=max_num_vertices)


def dgl_subgraph(csr, *vids, return_mapping=False):
    from .. import ops as _ops
    op = _ops.get_op("_contrib_dgl_subgraph")
    raw = _csr_pieces(csr) + [v._data for v in vids]
    outs, _ = _ops.invoke(op, raw, {"num_args": 3 + len(vids),
                                    "return_mapping": return_mapping})
    res = []
    for g in range(len(vids)):
        n = int(vids[g].shape[0])
        res.append(_mk_csr(outs[3 * g], outs[3 * g + 1], outs[3 * g + 2],
                           (n, n), csr.context))
    if return_mapping:
        off = 3 * len(vids)
        for g in range(len(vids)):
            n = int(vids[g].shape[0])
            res.append(_mk_csr(outs[off + 3 * g], outs[off + 3 * g + 1],
                               outs[off + 3 * g + 2], (n, n),
                               csr.context))
    return res if len(res) > 1 else res[0]


def dgl_adjacency(csr):
    from .. import ops as _ops
    op = _ops.get_op("_contrib_dgl_adjacency")
    outs, _ = _ops.invoke(op, _csr_pieces(csr), {})
    return _mk_csr(outs[0], outs[1], outs[2], csr.shape, csr.context)


def dgl_graph_compact(*args, return_mapping=False, graph_sizes=()):
    """``dgl_graph_compact(csr1, ..., csrN, vids1, ..., vidsN, ...)`` —
    the reference calling convention (dgl_graph.cc SubgraphCompact):
    each sampled subgraph CSR is paired with the neighbor-sample op's
    vertex-id array, and every column id is renumbered through it."""
    from .. import ops as _ops
    op = _ops.get_op("_contrib_dgl_graph_compact")
    if len(args) % 2:
        raise ValueError("dgl_graph_compact takes N csr graphs followed "
                         "by N vertex-id arrays")
    n_g = len(args) // 2
    csrs, vids = args[:n_g], args[n_g:]
    raw = []
    for c in csrs:
        raw.extend(_csr_pieces(c))
    raw.extend(v._data for v in vids)
    outs, _ = _ops.invoke(op, raw, {"num_args": len(raw),
                                    "return_mapping": return_mapping,
                                    "graph_sizes": tuple(graph_sizes)})
    res = []
    for g, c in enumerate(csrs):
        size = int(graph_sizes[g]) if g < len(graph_sizes) \
            else c.shape[0]
        res.append(_mk_csr(outs[3 * g], outs[3 * g + 1],
                           outs[3 * g + 2], (size, size), c.context))
    if return_mapping:
        off = 3 * n_g
        for g, c in enumerate(csrs):
            size = int(graph_sizes[g]) if g < len(graph_sizes) \
                else c.shape[0]
            res.append(_mk_csr(outs[off + 3 * g], outs[off + 3 * g + 1],
                               outs[off + 3 * g + 2], (size, size),
                               c.context))
    return res if len(res) > 1 else res[0]


def _install_contrib_ops():
    from ..contrib._alias import install_contrib_ops
    from . import register as _register
    install_contrib_ops(globals(), _register.make_stub)


_install_contrib_ops()
