"""Imperative control flow (reference: python/mxnet/ndarray/contrib.py
foreach/while_loop/cond).

Eager semantics: the loop runs on the host, each iteration's ops are
recorded on the autograd tape, so gradients flow with no extra
machinery (the reference builds a subgraph op even eagerly; we match
its *semantics* — for the compiled/XLA-native path use the symbolic
`sym.contrib.foreach` & co., or hybridize, which lower to one
``lax.scan``).

Divergence (documented): ``while_loop`` zero-fills the rows of the
stacked outputs beyond the executed step count; the reference leaves
them undefined.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["foreach", "while_loop", "cond"]


def _as_list(x):
    if x is None:
        return [], True
    if isinstance(x, (list, tuple)):
        return list(x), False
    return [x], True


def foreach(body, data, init_states):
    """Run ``body`` over dim 0 of ``data``; body(data_item, states) ->
    (outputs, new_states). Returns (stacked_outputs, final_states)."""
    from . import stack as _stack
    data_list, data_single = _as_list(data)
    states, states_single = _as_list(init_states)
    if not data_list:
        raise MXNetError("foreach needs at least one data input")
    length = data_list[0].shape[0]
    for d in data_list[1:]:
        if d.shape[0] != length:
            raise MXNetError("foreach data inputs disagree on dim 0")

    collected = None
    outs_single = True
    for i in range(length):
        eles = [d[i] for d in data_list]
        outs, states = body(eles[0] if data_single else eles,
                            states[0] if states_single else list(states))
        outs, outs_single = _as_list(outs)
        states, _ = _as_list(states)
        if collected is None:
            collected = [[] for _ in outs]
        for slot, o in zip(collected, outs):
            slot.append(o)
    stacked = [_stack(*slot, axis=0) for slot in (collected or [])]
    return (stacked[0] if outs_single and stacked else stacked,
            states[0] if states_single else states)


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Run ``func`` while ``cond`` holds, at most ``max_iterations``
    times; cond(*loop_vars) -> scalar, func(*loop_vars) -> (outputs,
    new_loop_vars). Stacked outputs have max_iterations rows (tail
    zero-filled); also returns the final loop_vars."""
    from . import stack as _stack, zeros_like as _zeros_like
    loop_vars, single_var = _as_list(loop_vars)
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    if not loop_vars:
        raise MXNetError("while_loop requires at least one loop var")

    collected = None
    outs_single = True
    steps = 0
    while steps < int(max_iterations) and \
            bool(cond(*loop_vars).asnumpy().reshape(())):
        step = func(*loop_vars)
        if not (isinstance(step, tuple) and len(step) == 2):
            raise MXNetError(
                "while_loop func must return (outputs, new_loop_vars)")
        outs, new_vars = step
        outs, outs_single = _as_list(outs)
        new_vars, _ = _as_list(new_vars)
        if len(new_vars) != len(loop_vars):
            raise MXNetError(
                "while_loop func returned %d loop_vars, expected %d"
                % (len(new_vars), len(loop_vars)))
        loop_vars = new_vars
        if collected is None:
            collected = [[] for _ in outs]
        for slot, o in zip(collected, outs):
            slot.append(o)
        steps += 1

    if collected is None:
        raise MXNetError(
            "while_loop executed zero steps; cannot infer output shapes "
            "(the reference raises here too)")
    stacked = []
    for slot in collected:
        pad = [_zeros_like(slot[0])] * (int(max_iterations) - len(slot))
        stacked.append(_stack(*(slot + pad), axis=0))
    return (stacked[0] if outs_single else stacked,
            loop_vars[0] if single_var else loop_vars)


def cond(pred, then_func, else_func):
    """Run one branch based on scalar ``pred`` (an NDArray); the branch
    functions take no arguments (they close over outer NDArrays)."""
    taken = bool(pred.asnumpy().reshape(()))
    return then_func() if taken else else_func()


def _install_contrib_ops():
    from ..contrib._alias import install_contrib_ops
    from . import register as _register
    install_contrib_ops(globals(), _register.make_stub)


_install_contrib_ops()
