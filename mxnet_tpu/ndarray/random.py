"""``mx.nd.random`` namespace (parity: python/mxnet/ndarray/random.py).

Scalar-parameter calls route to ``_random_*`` ops; NDArray-parameter
calls route to ``_sample_*`` ops, matching the reference's dispatch
(python/mxnet/ndarray/random.py:36 _random_helper).
"""
from __future__ import annotations

from .ndarray import NDArray, invoke_nd
from ..context import current_context

__all__ = ["uniform", "normal", "randn", "poisson", "exponential", "gamma",
           "multinomial", "negative_binomial", "generalized_negative_binomial",
           "randint", "shuffle"]


def _random(op_scalar, op_tensor, params, scalar_kwargs, shape, dtype, ctx,
            out):
    if any(isinstance(p, NDArray) for p in params):
        tensors = [p for p in params]
        return invoke_nd(op_tensor, tensors,
                         {"shape": shape, "dtype": dtype}, out=out)
    attrs = dict(scalar_kwargs)
    attrs.update({"shape": shape, "dtype": dtype})
    return invoke_nd(op_scalar, [], attrs, ctx=ctx or current_context(),
                     out=out)


def uniform(low=0, high=1, shape=(), dtype="float32", ctx=None, out=None,
            **kwargs):
    return _random("_random_uniform", "_sample_uniform", [low, high],
                   {"low": low, "high": high}, shape, dtype, ctx, out)


def normal(loc=0, scale=1, shape=(), dtype="float32", ctx=None, out=None,
           **kwargs):
    return _random("_random_normal", "_sample_normal", [loc, scale],
                   {"loc": loc, "scale": scale}, shape, dtype, ctx, out)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None, **kwargs):
    return normal(loc=loc, scale=scale, shape=shape, dtype=dtype, ctx=ctx)


def poisson(lam=1, shape=(), dtype="float32", ctx=None, out=None, **kwargs):
    return _random("_random_poisson", "_sample_poisson", [lam],
                   {"lam": lam}, shape, dtype, ctx, out)


def exponential(scale=1, shape=(), dtype="float32", ctx=None, out=None,
                **kwargs):
    lam = 1.0 / scale if not isinstance(scale, NDArray) else scale
    return _random("_random_exponential", "_sample_exponential", [lam],
                   {"lam": lam if not isinstance(lam, NDArray) else None},
                   shape, dtype, ctx, out)


def gamma(alpha=1, beta=1, shape=(), dtype="float32", ctx=None, out=None,
          **kwargs):
    return _random("_random_gamma", "_sample_gamma", [alpha, beta],
                   {"alpha": alpha, "beta": beta}, shape, dtype, ctx, out)


def negative_binomial(k=1, p=1, shape=(), dtype="float32", ctx=None,
                      out=None, **kwargs):
    return _random("_random_negative_binomial", "_sample_negative_binomial",
                   [k, p], {"k": k, "p": p}, shape, dtype, ctx, out)


def generalized_negative_binomial(mu=1, alpha=1, shape=(), dtype="float32",
                                  ctx=None, out=None, **kwargs):
    return _random("_random_generalized_negative_binomial",
                   "_sample_generalized_negative_binomial",
                   [mu, alpha], {"mu": mu, "alpha": alpha}, shape, dtype,
                   ctx, out)


def randint(low, high, shape=(), dtype="int32", ctx=None, out=None, **kwargs):
    return invoke_nd("_random_randint", [],
                     {"low": low, "high": high, "shape": shape,
                      "dtype": dtype}, ctx=ctx or current_context(), out=out)


def multinomial(data, shape=(), get_prob=False, out=None, dtype="int32",
                **kwargs):
    return invoke_nd("_sample_multinomial", [data],
                     {"shape": shape, "get_prob": get_prob, "dtype": dtype},
                     out=out)


def shuffle(data, **kwargs):
    return invoke_nd("_shuffle", [data], {})
