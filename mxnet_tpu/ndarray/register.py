"""Code-generated NDArray op namespace.

Parity with python/mxnet/ndarray/register.py: the reference generates
python functions at import time from the C++ op registry
(MXSymbolGetAtomicSymbolInfo); here we generate them from
``mxnet_tpu.ops``. Stubs accept tensors positionally or by name
(arg_names order), forward remaining kwargs as attributes, and support
``out=``.
"""
from __future__ import annotations

from .. import ops as _ops
from .ndarray import NDArray, invoke_nd

__all__ = ["make_stub", "install_ops"]


def make_stub(op):
    def stub(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        ctx = kwargs.pop("ctx", None)
        tensors = []
        pos_attrs = []
        for a in args:
            if a is None:
                continue
            if isinstance(a, NDArray):
                tensors.append(a)
            elif isinstance(a, (list, tuple)) and a \
                    and all(isinstance(x, NDArray) for x in a):
                tensors.extend(a)
            else:
                pos_attrs.append(a)
        if pos_attrs:
            # trailing positional parameters map onto the op's attrs in
            # declaration order (MXNet generated stubs accept this, e.g.
            # nd.clip(x, 0, 1))
            free = [k for k in op.defaults
                    if k not in kwargs and not k.startswith("__")]
            for k, v in zip(free, pos_attrs):
                kwargs[k] = v
        named = {k: kwargs.pop(k) for k in list(kwargs)
                 if isinstance(kwargs[k], NDArray)}
        if named:
            arg_names = op.resolve_arg_names(kwargs, num_inputs=len(named))
            bound = dict(zip(arg_names, tensors))
            bound.update(named)
            tensors = [bound[n] for n in arg_names if n in bound]
        if op.key_var_num_args and op.key_var_num_args not in kwargs:
            kwargs[op.key_var_num_args] = len(tensors)
        return invoke_nd(op, tensors, kwargs, out=out, ctx=ctx)

    stub.__name__ = op.name
    stub.__doc__ = op.doc_signature()
    return stub


def install_ops(namespace):
    """Install one stub per registered op into ``namespace`` (a dict)."""
    seen = {}
    for name in _ops.list_ops():
        op = _ops.get_op(name)
        if id(op) not in seen:
            seen[id(op)] = make_stub(op)
        namespace.setdefault(name, seen[id(op)])
    return namespace
