"""mxlint engine: file walking, AST contexts, suppressions, baseline.

A rule is a function ``rule(ctx) -> iterable[Violation]`` registered
under a kebab-case name via :func:`rule`.  The engine parses each file
ONCE into a :class:`FileCtx` (AST + parent links + import aliases) and
hands the same context to every rule — the tree-wide run over the
whole package is a tier-1 test, so the suite must stay linear in
source size (no per-rule re-parsing, no subprocesses).
"""
from __future__ import annotations

import ast
import json
import os
import re
import time
from typing import Callable, Dict, Iterable, List, Optional

__all__ = ["Violation", "FileCtx", "LintResult", "rule", "RULES",
           "rule_names", "lint_source", "lint_paths", "load_baseline",
           "default_baseline_path", "package_root"]

_SUPPRESS_RE = re.compile(
    r"#\s*mxlint:\s*disable=([a-zA-Z0-9_,\- ]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*mxlint:\s*disable-file=([a-zA-Z0-9_,\- ]+)")


class Violation:
    """One finding: ``rule`` (kebab-case name), ``path`` (normalized,
    ``mxnet_tpu/...`` when under the package), 1-based ``line``/
    ``col``, human ``message``, and ``context`` — the stripped source
    line, which is also the baseline-matching key (line numbers drift;
    code text identifies the site)."""

    __slots__ = ("rule", "path", "line", "col", "message", "context")

    def __init__(self, rule, path, line, col, message, context=""):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.message = message
        self.context = context

    def key(self):
        return (self.rule, self.path, self.context)

    def to_dict(self):
        return {"rule": self.rule, "path": self.path,
                "line": self.line, "col": self.col,
                "message": self.message, "context": self.context}

    def __repr__(self):
        return "%s:%d:%d: [%s] %s" % (self.path, self.line, self.col,
                                      self.rule, self.message)


class _Aliases:
    """Module-level import aliases the rules care about, resolved
    once per file: ``modules`` maps local name -> dotted module
    ("jax", "numpy", "threading", "queue", "time", "os", "random"),
    ``names`` maps local name -> (module, original name) for
    from-imports ("from jax import jit as J" -> J: ("jax", "jit"))."""

    def __init__(self, tree):
        self.modules: Dict[str, str] = {}
        self.names: Dict[str, tuple] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.modules[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom):
                if node.module is None:
                    # relative module import: ``from . import envs``
                    # binds each name as a MODULE alias — the tree's
                    # standard intra-package idiom
                    for a in node.names:
                        self.modules[a.asname or a.name] = a.name
                else:
                    for a in node.names:
                        self.names[a.asname or a.name] = (node.module,
                                                          a.name)

    def module_is(self, name, dotted):
        """True when local ``name`` is module ``dotted`` (exact or the
        relative tail: ``from . import envs`` binds "envs")."""
        mod = self.modules.get(name)
        if mod == dotted or (mod or "").endswith("." + dotted):
            return True
        ref = self.names.get(name)
        return ref is not None and (ref[1] == dotted
                                    or ref[1].endswith("." + dotted))

    def name_is(self, name, module, orig):
        """True when local ``name`` came from ``from module import
        orig`` (module matched on its dotted tail, so relative
        imports count)."""
        ref = self.names.get(name)
        if ref is None:
            return False
        mod, bound = ref
        return bound == orig and (mod == module
                                  or mod.endswith(module)
                                  or module.endswith(mod))


class FileCtx:
    """Everything a rule needs for one file, computed once."""

    def __init__(self, path, relpath, source, tree):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.aliases = _Aliases(tree)
        # one walk for everything: rules iterate ``nodes`` instead of
        # re-walking per rule (the tree-wide run is a tier-1 test —
        # linear passes keep it inside its wall-time budget)
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.nodes = [tree]
        for parent in self.nodes:
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
                self.nodes.append(child)

    # -- helpers shared by rules ------------------------------------------
    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def violation(self, rule_name, node, message):
        return Violation(rule_name, self.relpath,
                         getattr(node, "lineno", 0),
                         getattr(node, "col_offset", 0) + 1,
                         message, self.line_text(
                             getattr(node, "lineno", 0)))

    def ancestors(self, node):
        while node in self.parents:
            node = self.parents[node]
            yield node

    def enclosing_function(self, node):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def under_with_matching(self, node, pattern):
        """True when ``node`` sits lexically inside a ``with`` whose
        context expression's source text matches ``pattern`` (a
        compiled regex) — the "holds its lock" check."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    try:
                        txt = ast.unparse(item.context_expr)
                    except Exception:
                        txt = ""
                    if pattern.search(txt):
                        return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                # a lock held by a caller does not extend into a
                # nested function body that may run on another thread
                return False
        return False

    def call_name(self, call):
        """("jax", "jit") for ``jax.jit(...)`` / aliased forms;
        (None, "open") for a bare call; (None, None) when the callee
        is not a name/attribute."""
        fn = call.func
        if isinstance(fn, ast.Name):
            return None, fn.id
        if isinstance(fn, ast.Attribute) and isinstance(fn.value,
                                                        ast.Name):
            return fn.value.id, fn.attr
        return None, None


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

RULES: Dict[str, Callable] = {}
_RULE_DOCS: Dict[str, str] = {}


def rule(name, doc):
    """Register a rule under its kebab-case ``name`` with a one-line
    ``doc`` (rendered by ``--list-rules`` and the README table)."""
    def deco(fn):
        RULES[name] = fn
        _RULE_DOCS[name] = doc
        fn.rule_name = name
        fn.rule_doc = doc
        return fn
    return deco


def rule_names():
    return sorted(RULES)


def rule_docs():
    return dict(_RULE_DOCS)


# ---------------------------------------------------------------------------
# per-file run
# ---------------------------------------------------------------------------

def _normalize(path):
    """Report paths as ``mxnet_tpu/...`` whenever the file lives under
    the package — baseline entries must match no matter which working
    directory or absolute prefix the lint ran from."""
    norm = os.path.normpath(path).replace(os.sep, "/")
    idx = norm.rfind("mxnet_tpu/")
    return norm[idx:] if idx >= 0 else norm


def _suppressions(lines):
    per_line: Dict[int, set] = {}
    file_wide: set = set()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            rules = {r.strip() for r in m.group(1).split(",")
                     if r.strip()}
            per_line.setdefault(i, set()).update(rules)
        m = _SUPPRESS_FILE_RE.search(text)
        if m and i <= 10:
            file_wide.update(r.strip() for r in m.group(1).split(",")
                             if r.strip())
    return per_line, file_wide


def lint_source(source, path="<string>", rules=None,
                count_suppressed=None):
    """Lint one source string; returns the UNSUPPRESSED violations.
    ``rules`` optionally restricts to a subset of rule names.
    ``count_suppressed`` (a list) collects suppressed findings."""
    from . import rules as _rules_mod  # noqa: F401 — registers RULES
    relpath = _normalize(path)
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [Violation("parse-error", relpath, exc.lineno or 0,
                          exc.offset or 0, "cannot parse: %s" % exc)]
    ctx = FileCtx(path, relpath, source, tree)
    per_line, file_wide = _suppressions(ctx.lines)
    active = RULES if rules is None else {
        n: RULES[n] for n in rules}
    out = []
    for name, fn in active.items():
        for v in fn(ctx):
            if v.rule in file_wide or \
                    v.rule in per_line.get(v.line, ()):
                if count_suppressed is not None:
                    count_suppressed.append(v)
                continue
            out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def package_root():
    """Absolute path of the ``mxnet_tpu`` package directory."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_baseline_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path=None):
    """The committed baseline: ``{"entries": [{"rule", "path",
    "context", "rationale"}]}``.  Every entry MUST carry a non-empty
    rationale — a grandfathered violation without a written reason is
    itself an error."""
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    entries = data.get("entries", [])
    for e in entries:
        if not str(e.get("rationale", "")).strip():
            raise ValueError(
                "baseline %s: entry %r has no rationale — every "
                "grandfathered violation must say why" % (path, e))
    return entries


class LintResult:
    def __init__(self, violations, baselined, suppressed, files,
                 elapsed_s, stale_baseline):
        self.violations = violations        # non-baselined findings
        self.baselined = baselined          # matched baseline entries
        self.suppressed = suppressed        # inline-suppressed count
        self.files = files
        self.elapsed_s = elapsed_s
        self.stale_baseline = stale_baseline  # entries matching nothing

    @property
    def ok(self):
        return not self.violations

    def counts(self):
        by_rule: Dict[str, int] = {}
        for v in self.violations:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        return by_rule

    def to_dict(self):
        return {
            "version": 1,
            "ok": self.ok,
            "files": self.files,
            "elapsed_s": round(self.elapsed_s, 3),
            "counts": self.counts(),
            "violations": [v.to_dict() for v in self.violations],
            "baselined": [v.to_dict() for v in self.baselined],
            "suppressed": self.suppressed,
            "stale_baseline": self.stale_baseline,
        }


def _walk_py(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def lint_paths(paths=None, rules=None, baseline=None,
               use_baseline=True):
    """Lint files/directories (default: the installed ``mxnet_tpu``
    package).  Baseline entries absorb matching findings; entries that
    match nothing are reported in ``stale_baseline`` so the file never
    accretes dead weight."""
    t0 = time.perf_counter()
    if paths is None or not list(paths):
        paths = [package_root()]
    entries = []
    if use_baseline:
        entries = baseline if isinstance(baseline, list) \
            else load_baseline(baseline)
    bl_index = {}
    for e in entries:
        bl_index.setdefault(
            (e["rule"], e["path"], e.get("context", "")), e)
    matched = set()
    violations: List[Violation] = []
    baselined: List[Violation] = []
    suppressed: List[Violation] = []
    files = 0
    seen_paths = set()
    for fname in _walk_py(paths):
        files += 1
        seen_paths.add(_normalize(fname))
        try:
            with open(fname, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as exc:
            violations.append(Violation(
                "parse-error", _normalize(fname), 0, 0,
                "cannot read: %s" % exc))
            continue
        for v in lint_source(source, fname, rules=rules,
                             count_suppressed=suppressed):
            key = v.key()
            if key in bl_index:
                matched.add(key)
                baselined.append(v)
            else:
                violations.append(v)
    # an entry is stale only when its file WAS linted and nothing
    # matched — linting a subtree must not flag the rest of the
    # baseline as dead
    stale = [e for k, e in bl_index.items()
             if k not in matched and e["path"] in seen_paths]
    return LintResult(violations, baselined, len(suppressed), files,
                      time.perf_counter() - t0, stale)
