"""The mxlint rules — each encodes one convention a real bug paid for.

Every rule is AST-based (no regex-over-source except comment
handling), individually suppressible with ``# mxlint: disable=<rule>``
and baselinable with a written rationale.  False-positive philosophy:
a rule may be conservative (miss exotic constructions) but must not be
noisy — a finding the tree cannot fix or baseline honestly is a bug in
the rule, not the tree.
"""
from __future__ import annotations

import ast
import json
import os
import re

from .core import rule

# ---------------------------------------------------------------------------
# jit-staging: no raw jax.jit outside compile_watch.py
# ---------------------------------------------------------------------------

_JIT_EXEMPT_FILES = (
    # the staging choke point itself: its jax.jit twin IS the rule's
    # blessed destination
    "mxnet_tpu/compile_watch.py",
)


def _jit_allowlist_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "jit_allowlist.json")


_JIT_ALLOWLIST_CACHE = None


def load_jit_allowlist():
    """Per-file allowlist for sites where staging is genuinely WRONG
    (not merely unmigrated) — each entry documents why.  Cached: the
    tree-wide run consults it once per file."""
    global _JIT_ALLOWLIST_CACHE
    if _JIT_ALLOWLIST_CACHE is not None:
        return _JIT_ALLOWLIST_CACHE
    path = _jit_allowlist_path()
    if not os.path.exists(path):
        _JIT_ALLOWLIST_CACHE = {}
        return _JIT_ALLOWLIST_CACHE
    with open(path) as f:
        data = json.load(f)
    out = {}
    for e in data.get("entries", []):
        if not str(e.get("rationale", "")).strip():
            raise ValueError(
                "jit_allowlist.json: entry %r has no rationale" % e)
        out[e["path"]] = e["rationale"]
    _JIT_ALLOWLIST_CACHE = out
    return out


@rule("jit-staging",
      "every jax.jit stages through compile_watch.jit (compile "
      "telemetry, storm detection, persistent compile cache)")
def check_jit_staging(ctx):
    if ctx.relpath in _JIT_EXEMPT_FILES:
        return
    allow = load_jit_allowlist()
    if ctx.relpath in allow:
        return
    al = ctx.aliases

    def is_raw_jit(expr):
        """True when ``expr`` references jax's jit: ``jax.jit`` /
        an alias / ``from jax import jit``."""
        if isinstance(expr, ast.Attribute) and expr.attr == "jit" \
                and isinstance(expr.value, ast.Name) \
                and al.module_is(expr.value.id, "jax"):
            return True
        return isinstance(expr, ast.Name) \
            and al.name_is(expr.id, "jax", "jit")

    msg = ("raw jax.jit — stage through compile_watch.jit("
           "fn, site=...) so this program joins compile "
           "telemetry, storm detection and the persistent "
           "compile cache (or add a jit_allowlist.json entry "
           "with a rationale)")
    # decorator forms: @jax.jit / @jit / @partial(jax.jit, ...) —
    # the most common jit idiom must not bypass the gate
    dec_calls = set()
    for node in ctx.nodes:
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            args = dec.args if isinstance(dec, ast.Call) else []
            if isinstance(dec, ast.Call):
                dec_calls.add(id(dec))       # no double report below
            if is_raw_jit(target) or any(is_raw_jit(a)
                                         for a in args):
                yield ctx.violation("jit-staging", dec, msg)
    for node in ctx.nodes:
        if not isinstance(node, ast.Call) or id(node) in dec_calls:
            continue
        if is_raw_jit(node.func):
            yield ctx.violation("jit-staging", node, msg)


# ---------------------------------------------------------------------------
# atomic-write: durable writes go tmp + os.replace
# ---------------------------------------------------------------------------

_WRITE_MODES = re.compile(r"^[wx]b?\+?$")


def _open_mode(call):
    """The mode string of an ``open`` call, or None when dynamic."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _scope_calls_os_replace(ctx, node):
    """True when the enclosing function (or module body, for
    module-level writes) also calls ``os.replace``/``os.rename`` —
    the write-then-rename discipline in one scope."""
    scope = ctx.enclosing_function(node) or ctx.tree
    for sub in ast.walk(scope):
        if isinstance(sub, ast.Call):
            base, attr = ctx.call_name(sub)
            if attr in ("replace", "rename") and base is not None \
                    and ctx.aliases.module_is(base, "os"):
                return True
    return False


@rule("atomic-write",
      "no bare open(..., 'w'/'wb') of durable files — write tmp then "
      "os.replace (a preempted save must leave the old file intact)")
def check_atomic_write(ctx):
    for node in ctx.nodes:
        if not isinstance(node, ast.Call):
            continue
        base, attr = ctx.call_name(node)
        if attr != "open" or base is not None:
            continue
        mode = _open_mode(node)
        if mode is None or not _WRITE_MODES.match(mode):
            continue                     # reads, appends, dynamic
        if _scope_calls_os_replace(ctx, node):
            continue
        yield ctx.violation(
            "atomic-write", node,
            "bare open(..., %r) write without os.replace in scope — "
            "write to a tmp name and os.replace() it (see "
            "base.atomic_write_bytes)" % mode)


# ---------------------------------------------------------------------------
# counter-lock: telemetry/profiler counter bumps hold their lock
# ---------------------------------------------------------------------------

# the shared-counter attribute names of the observability stack; a
# += / -= on one of these OUTSIDE a with-lock is exactly the PR 3
# racy-counter bug shape.  Bare local names are never flagged.
_COUNTER_ATTRS = frozenset({
    "compile_count", "compile_total_s", "cache_hits", "cache_hit_s",
    "degraded", "dispatches", "step_flops", "step_bytes",
    "step_dispatches", "step_compiles", "step_compile_s",
    "total_flops", "total_bytes", "hits", "misses", "errors",
    "evictions", "stores", "stores_dropped", "bytes_read",
    "bytes_written", "hit_s", "saves", "failures", "records_dropped",
    "dropped", "steps", "samples",
})

# dict containers whose item-writes count as counter mutations
_COUNTER_SUBSCRIPTS = ("counters", "aggregate")

_LOCKISH = re.compile(r"lock|_mu\b|mutex|cond", re.IGNORECASE)

# modules where the counter conventions apply (the observability
# stack + its writers); elsewhere ad-hoc counters are local state
_COUNTER_MODULES = (
    "mxnet_tpu/profiler.py", "mxnet_tpu/telemetry.py",
    "mxnet_tpu/compile_watch.py", "mxnet_tpu/compile_cache.py",
    "mxnet_tpu/livemetrics.py", "mxnet_tpu/tracing.py",
    "mxnet_tpu/checkpoint.py", "mxnet_tpu/serving/",
    "mxnet_tpu/bucketing/record.py",
)


def _counter_target(node):
    """The flagged component name when ``node`` (an assignment
    target) mutates shared counter state, else None."""
    if isinstance(node, ast.Attribute):
        if node.attr in _COUNTER_ATTRS:
            return node.attr
    if isinstance(node, ast.Subscript):
        # _state["counters"][name] = ... / ["aggregate"] writes
        inner = node.value
        if isinstance(inner, ast.Subscript) and \
                isinstance(inner.slice, ast.Constant) and \
                inner.slice.value in _COUNTER_SUBSCRIPTS:
            return '["%s"]' % inner.slice.value
    return None


@rule("counter-lock",
      "observability counter mutations (+=) hold their designated "
      "lock — racy counters were PR 3's bug")
def check_counter_lock(ctx):
    if not any(ctx.relpath.startswith(m) or ctx.relpath == m
               for m in _COUNTER_MODULES):
        return
    for node in ctx.nodes:
        if isinstance(node, ast.AugAssign):
            name = _counter_target(node.target)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Subscript):
            name = _counter_target(node.targets[0])
        else:
            continue
        if name is None:
            continue
        fn = ctx.enclosing_function(node)
        if fn is None and not isinstance(
                ctx.parents.get(node), (ast.With, ast.AsyncWith)):
            continue                 # module-level init, not mutation
        if fn is not None and fn.name in ("__init__",):
            continue                 # constructor: no concurrent view
        if fn is not None and fn.name.endswith("_locked"):
            # the tree's caller-holds-the-lock convention: the
            # ``_locked`` suffix IS the contract (and the rule checks
            # every caller site takes a lock around such calls is out
            # of scope for a lexical pass)
            continue
        if ctx.under_with_matching(node, _LOCKISH):
            continue
        yield ctx.violation(
            "counter-lock", node,
            "counter %s mutated outside a with-lock block — take "
            "the module/object lock (or suppress with a rationale "
            "if the caller provably holds it)" % name)


# ---------------------------------------------------------------------------
# thread-hygiene: daemon-or-drained threads, bounded queues
# ---------------------------------------------------------------------------

_PIPELINE_MODULES = (
    "mxnet_tpu/io/", "mxnet_tpu/serving/", "mxnet_tpu/checkpoint.py",
    "mxnet_tpu/compile_cache.py", "mxnet_tpu/bucketing/",
    "mxnet_tpu/kvstore_server.py", "mxnet_tpu/livemetrics.py",
)


def _kw(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


@rule("thread-hygiene",
      "threading.Thread sites are daemon=True (or suppressed with "
      "their join/drain path named); queue.Queue() in pipeline/"
      "writer modules declares a maxsize (bounded backpressure)")
def check_thread_hygiene(ctx):
    al = ctx.aliases
    for node in ctx.nodes:
        if not isinstance(node, ast.Call):
            continue
        base, attr = ctx.call_name(node)
        # Thread(...) without daemon=True
        is_thread = (attr == "Thread" and (
            (base is not None and al.module_is(base, "threading"))
            or (base is None and al.name_is(attr, "threading",
                                            "Thread"))))
        if is_thread:
            daemon = _kw(node, "daemon")
            if not (isinstance(daemon, ast.Constant)
                    and daemon.value is True):
                yield ctx.violation(
                    "thread-hygiene", node,
                    "threading.Thread without daemon=True — a "
                    "non-daemon worker must be suppressed here with "
                    "a comment naming its join/drain path (PR 4's "
                    "blocking-put leak)")
            continue
        # unbounded queue.Queue() in pipeline/writer modules
        if not any(ctx.relpath.startswith(m) for m in
                   _PIPELINE_MODULES):
            continue
        is_queue = (attr in ("Queue", "LifoQueue",
                             "PriorityQueue") and (
            (base is not None and al.module_is(base, "queue"))
            or (base is None and al.name_is(attr, "queue", attr))))
        if is_queue:
            size = node.args[0] if node.args else _kw(node, "maxsize")
            unbounded = size is None or (
                isinstance(size, ast.Constant) and
                not size.value)
            if unbounded:
                yield ctx.violation(
                    "thread-hygiene", node,
                    "queue.Queue() without maxsize in a pipeline/"
                    "writer module — unbounded queues hide "
                    "backpressure until the host OOMs; bound it or "
                    "suppress naming the upstream bound")


# ---------------------------------------------------------------------------
# traced-purity: no host impurities inside functions handed to jit
# ---------------------------------------------------------------------------

_IMPURE_TIME = ("time", "perf_counter", "monotonic", "time_ns",
                "process_time")


def _collect_traced_functions(ctx):
    """FunctionDefs that become traced programs: (a) passed by name
    as the first argument to any ``*jit(...)`` call in the same file,
    (b) decorated with ``@jit``/``@jax.jit``/``@partial(jit, ...)``,
    (c) nested inside a function named ``fused_step_fn`` (the fused
    optimizer-update roster) and returned from it."""
    defs = {}
    for node in ctx.nodes:
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, []).append(node)
    traced = []
    for node in ctx.nodes:
        if isinstance(node, ast.Call):
            _, attr = ctx.call_name(node)
            if attr == "jit" and node.args and \
                    isinstance(node.args[0], ast.Name):
                # closest preceding def wins (shadowing is rare and
                # per-scope matching would cost more than it buys)
                for cand in defs.get(node.args[0].id, ()):
                    traced.append(cand)
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                d = dec
                if isinstance(d, ast.Call):
                    if d.args and isinstance(d.args[0], (ast.Name,
                                                         ast.Attribute)):
                        first = d.args[0]
                        if (isinstance(first, ast.Name)
                                and first.id == "jit") or \
                           (isinstance(first, ast.Attribute)
                                and first.attr == "jit"):
                            traced.append(node)
                            break
                    d = d.func
                if (isinstance(d, ast.Name) and d.id == "jit") or \
                        (isinstance(d, ast.Attribute)
                         and d.attr == "jit"):
                    traced.append(node)
                    break
            if node.name == "fused_step_fn" or \
                    node.name.startswith("fused_step_fn"):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.FunctionDef) and sub is not node:
                        traced.append(sub)
    return traced


@rule("traced-purity",
      "no time.time()/np.random/global mutation/os.environ inside "
      "functions handed to jit or fused_step_fn — host impurities "
      "silently bake into the compiled program as constants")
def check_traced_purity(ctx):
    al = ctx.aliases
    seen = set()
    for fn in _collect_traced_functions(ctx):
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                yield ctx.violation(
                    "traced-purity", node,
                    "global statement inside traced function %r — "
                    "the mutation runs at TRACE time only, then "
                    "never again" % fn.name)
            if not isinstance(node, ast.Call):
                continue
            # np.random.<fn>(...) — callee is Attribute whose value
            # is Attribute(random) on a numpy alias (checked before
            # the two-component fast path below, which cannot see it)
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Attribute) and \
                    f.value.attr == "random" and \
                    isinstance(f.value.value, ast.Name) and \
                    (al.module_is(f.value.value.id, "numpy")
                     or f.value.value.id in ("np", "numpy", "_np")):
                yield ctx.violation(
                    "traced-purity", node,
                    "np.random.%s inside traced function %r is "
                    "sampled once at trace time and frozen into the "
                    "program — use jax.random with a threaded key"
                    % (f.attr, fn.name))
                continue
            if isinstance(f, ast.Attribute) and f.attr == "get" and \
                    isinstance(f.value, ast.Attribute) and \
                    f.value.attr == "environ":
                yield ctx.violation(
                    "traced-purity", node,
                    "os.environ read inside traced function %r is "
                    "evaluated at trace time only" % fn.name)
                continue
            base, attr = ctx.call_name(node)
            if base is None:
                continue
            if al.module_is(base, "time") and attr in _IMPURE_TIME:
                yield ctx.violation(
                    "traced-purity", node,
                    "time.%s() inside traced function %r bakes the "
                    "trace-time clock into the compiled program as "
                    "a constant — pass it in as an argument"
                    % (attr, fn.name))
            elif (al.module_is(base, "random")
                  and attr in ("random", "randint", "uniform",
                               "randrange", "choice", "shuffle",
                               "gauss", "normalvariate")):
                yield ctx.violation(
                    "traced-purity", node,
                    "python random.%s() inside traced function %r "
                    "is drawn once at trace time — thread a jax PRNG "
                    "key instead" % (attr, fn.name))


# ---------------------------------------------------------------------------
# env-registry: MXNET_* reads go through mxnet_tpu.envs
# ---------------------------------------------------------------------------

_ENV_EXEMPT_FILES = (
    "mxnet_tpu/envs.py",            # the registry reads os.environ
    "mxnet_tpu/tools/lint/",        # this package (fixture strings)
)


def _mxnet_const(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith("MXNET_"):
        return node.value
    return None


@rule("env-registry",
      "every MXNET_* read goes through the typed mxnet_tpu.envs "
      "registry (declared default + doc, MXNetError naming the "
      "variable on a malformed value)")
def check_env_registry(ctx):
    if any(ctx.relpath == m or ctx.relpath.startswith(m)
           for m in _ENV_EXEMPT_FILES):
        return
    # lazily import the registry for the declared-name check; the
    # lint must still run (minus that check) if envs cannot import
    try:
        from ... import envs as _envs
        declared = set(_envs.registry())
    except Exception:
        declared = None
    al = ctx.aliases
    for node in ctx.nodes:
        # os.environ["MXNET_X"] loads
        if isinstance(node, ast.Subscript):
            v = node.value
            if isinstance(v, ast.Attribute) and v.attr == "environ":
                name = _mxnet_const(node.slice)
                if name:
                    yield ctx.violation(
                        "env-registry", node,
                        "os.environ[%r] — read it through "
                        "mxnet_tpu.envs accessors" % name)
            continue
        if not isinstance(node, ast.Call):
            continue
        base, attr = ctx.call_name(node)
        name = _mxnet_const(node.args[0]) if node.args else None
        if name is None:
            continue
        # os.environ.get("MXNET_X") / environ.get(...)
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "get" and (
                (isinstance(f.value, ast.Attribute)
                 and f.value.attr == "environ")
                or (isinstance(f.value, ast.Name)
                    and al.name_is(f.value.id, "os", "environ"))):
            yield ctx.violation(
                "env-registry", node,
                "os.environ.get(%r) — read it through "
                "mxnet_tpu.envs accessors" % name)
            continue
        # os.getenv("MXNET_X")
        if attr == "getenv" and base is not None \
                and al.module_is(base, "os"):
            yield ctx.violation(
                "env-registry", node,
                "os.getenv(%r) — read it through mxnet_tpu.envs "
                "accessors" % name)
            continue
        # legacy base.get_env("MXNET_X", ...)
        if attr == "get_env":
            yield ctx.violation(
                "env-registry", node,
                "legacy get_env(%r) — use the typed mxnet_tpu.envs "
                "accessor (declared default + parse errors that "
                "name the variable)" % name)
            continue
        # envs.get_*("MXNET_TYPO") — statically check declarations
        if declared is not None and attr in (
                "get_bool", "get_int", "get_float", "get_str",
                "get_path", "get_raw") and base is not None \
                and al.module_is(base, "envs") \
                and name not in declared:
            yield ctx.violation(
                "env-registry", node,
                "envs.%s(%r): variable is not declared in "
                "mxnet_tpu/envs.py — declare it (typo?) before "
                "reading it" % (attr, name))
