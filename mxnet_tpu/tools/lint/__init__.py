"""mxlint — the framework's own static-analysis suite.

Twelve PRs of this codebase turned several hard-won bug fixes into
*conventions*: every ``jax.jit`` stages through ``compile_watch.jit``
(else it is invisible to compile telemetry, storm detection, and the
persistent compile cache), every durable artifact writes
tmp+``os.replace``, every telemetry/profiler counter bump holds its
lock, every worker thread is daemon-or-drained behind a bounded queue,
traced functions stay pure, and every ``MXNET_*`` knob reads through
the typed ``mxnet_tpu.envs`` registry.  Each rule here encodes one of
those conventions as a named, individually-suppressible AST check over
the framework's own source — the tier-1 test runs the whole suite over
``mxnet_tpu/`` and fails on any non-baselined violation, so the
conventions are machine-checked before ROADMAP's 4D-parallelism /
stateful-serving / multi-host growth multiplies the surface.

Usage::

    python -m mxnet_tpu.tools.lint                 # lint mxnet_tpu/
    python -m mxnet_tpu.tools.lint path/ --format json
    python -m mxnet_tpu.tools.lint --envs          # env-var reference
    python -m mxnet_tpu.tools.lint --list-rules

Suppress one finding inline with a trailing comment naming the rule::

    fn = jax.jit(fwd)   # mxlint: disable=jit-staging -- export path

Grandfathered sites live in the committed ``baseline.json`` next to
this package; every entry carries a one-line rationale and matches on
(rule, path, source line text) so line-number drift never resurrects
it.  The ``jit-staging`` rule additionally consults
``jit_allowlist.json`` — per-file entries whose rationale documents
why staging is *wrong* there, not merely unmigrated.
"""
from .core import (LintResult, Violation, lint_paths, lint_source,
                   load_baseline, RULES, rule_names)

__all__ = ["LintResult", "Violation", "lint_paths", "lint_source",
           "load_baseline", "RULES", "rule_names"]
