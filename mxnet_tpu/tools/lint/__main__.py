"""mxlint CLI — ``python -m mxnet_tpu.tools.lint``.

Exit status: 0 when no non-baselined violations (and no stale
baseline entries), 1 otherwise, 2 on usage errors.
"""
from __future__ import annotations

import argparse
import json
import sys

from .core import (default_baseline_path, lint_paths, rule_docs,
                   rule_names)


def _text_report(result, verbose=False):
    out = []
    for v in result.violations:
        out.append("%s:%d:%d: [%s] %s"
                   % (v.path, v.line, v.col, v.rule, v.message))
    for e in result.stale_baseline:
        out.append("baseline: stale entry (%s, %s) — the violation "
                   "is gone; delete the entry"
                   % (e["rule"], e["path"]))
    counts = result.counts()
    summary = ("%d file(s), %d violation(s)"
               % (result.files, len(result.violations)))
    if counts:
        summary += " [" + ", ".join(
            "%s=%d" % kv for kv in sorted(counts.items())) + "]"
    if result.baselined:
        summary += ", %d baselined" % len(result.baselined)
    if result.suppressed:
        summary += ", %d suppressed" % result.suppressed
    summary += ", %.2fs" % result.elapsed_s
    out.append(summary)
    if verbose and result.baselined:
        out.append("-- baselined --")
        for v in result.baselined:
            out.append("%s:%d: [%s] (baselined)"
                       % (v.path, v.line, v.rule))
    return "\n".join(out)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.tools.lint",
        description="mxlint: the framework's invariant checks "
                    "(see mxnet_tpu/tools/lint/__init__.py)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the "
                        "mxnet_tpu package)")
    p.add_argument("--format", choices=("text", "json"),
                   default="text")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline file (default: the committed %s)"
                        % default_baseline_path())
    p.add_argument("--no-baseline", action="store_true",
                   help="report grandfathered sites too")
    p.add_argument("--rules", default=None, metavar="R1,R2",
                   help="run only these rules")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--envs", action="store_true",
                   help="print the MXNET_* environment-variable "
                        "reference generated from mxnet_tpu.envs")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    if args.envs:
        from ... import envs
        print(envs.render_reference())
        return 0
    if args.list_rules:
        from . import rules as _rules  # noqa: F401
        docs = rule_docs()
        for name in rule_names():
            print("%-16s %s" % (name, docs.get(name, "")))
        return 0

    rules = None
    if args.rules:
        from . import rules as _rules  # noqa: F401
        rules = [r.strip() for r in args.rules.split(",")
                 if r.strip()]
        unknown = [r for r in rules if r not in rule_names()]
        if unknown:
            print("unknown rule(s): %s (have: %s)"
                  % (", ".join(unknown), ", ".join(rule_names())),
                  file=sys.stderr)
            return 2
    result = lint_paths(args.paths or None, rules=rules,
                        baseline=args.baseline,
                        use_baseline=not args.no_baseline)
    if args.format == "json":
        print(json.dumps(result.to_dict(), indent=2))
    else:
        print(_text_report(result, verbose=args.verbose))
    return 0 if (result.ok and not result.stale_baseline) else 1


if __name__ == "__main__":
    sys.exit(main())
