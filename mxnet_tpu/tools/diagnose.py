"""Environment diagnosis (parity: tools/diagnose.py, minus the
network-reachability section — this environment has zero egress, so
the equivalent signal is backend reachability: a short-timeout
subprocess probe of the accelerator, the same probe bench.py and the
TPU test lane use).

Run: ``python -m mxnet_tpu.tools.diagnose``.

Telemetry mode: ``python -m mxnet_tpu.tools.diagnose <run>.jsonl``
reads a ``mxnet_tpu.telemetry`` JSONL sink back into human tables —
step-time percentiles, per-phase breakdown, goodput (productive vs.
skipped/retried, unified with ``fault.stats()``), memory watermarks,
and per-key comms bytes/latency — plus, when the run was recorded with
``mxnet_tpu.compile_watch`` active, the compile log (per-program
compile count/seconds/causes, recompile storms, the fused-step cache
counters), the hardware-utilization table (MFU and memory-bandwidth
percentiles from the per-step ``utilization`` records), and — when the
run checkpointed through ``mxnet_tpu.checkpoint`` — the Checkpoints
table (per-save bytes/duration, blocking vs async split, failed saves,
last good epoch) plus the goodput line reconciling steps lost to a
resume rollback, and — when the run exchanged gradients through
``parallel.grad_sync`` (``MXNET_GRAD_OVERLAP=1``) — the Gradient sync
table (per-bucket bytes/latency, in-program step count, sync-phase
share), and — when the run hosted an ``mxnet_tpu.serving``
``InferenceServer`` — the Serving table (request counts with
shed/timeout splits, latency percentiles, requests/sec, bucket-ladder
occupancy, queue-depth peak vs bound, per-replica dispatch), and —
when a shape-bucketing producer ran (``mxnet_tpu.bucketing``) — the
Bucketing table (per-bucket batch counts, padding-overhead share,
pad-row and discarded-sample counts per producer), and — when the SLO
watchdog fired (``mxnet_tpu.livemetrics``, ``MXNET_WATCHDOG=1``) — the
Alerts table (step, alert kind, breach detail), and — when collectives
ran over a mesh — the Per-link comms table splitting each collective
kind's bytes into intra-host (``ici``) vs cross-host (``dcn``) traffic
(``parallel.mesh.link_split``), plus a Restarts goodput line
reconciling the supervised launcher's restart generation
(``MXNET_LAUNCH_RESTART``) with ``fault.stats()``'s resume counters. A truncated trailing
line (a run killed mid-append) is skipped with a one-line warning;
the rest of the report renders. This supersedes scraping the same
facts out of log lines with ``tools/parse_log.py``.

Fleet mode: pointing diagnose at a DIRECTORY (or a shell glob) of
per-rank/per-worker sinks renders the cross-rank report instead — a
skew table (per-rank step-time/data_wait deltas with slowest-rank
attribution and the restart-generation timeline) plus a fleet serving
rollup that joins router records against replica records across sinks
(``dispatched == admitted + shed``) and reconciles flight-recorder
bundles (``mxnet_tpu.flightrec``) against the ``replica_lost`` alerts
that triggered them. A torn sink or bundle becomes a counted WARNING
line, never an abort. ``--format json`` mirrors every table — single
file or fleet — as structured records; the default text output of the
single-file path is unchanged.
"""
from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import platform
import re
import subprocess
import sys


def diagnose_python():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("Arch         :", platform.architecture())


def diagnose_os():
    print("----------System Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("node         :", platform.node())
    print("release      :", platform.release())
    print("version      :", platform.version())


def diagnose_hardware():
    print("----------Hardware Info----------")
    print("machine      :", platform.machine())
    print("processor    :", platform.processor())
    if sys.platform.startswith("linux"):
        try:
            out = subprocess.run(["lscpu"], capture_output=True,
                                 text=True, timeout=10)
            print(out.stdout.strip())
        except Exception:
            pass


def diagnose_mxnet():
    print("----------MXNet-TPU Info----------")
    import mxnet_tpu as mx
    from mxnet_tpu import runtime
    print("Version      :", getattr(mx, "__version__", "dev"))
    print("Directory    :", os.path.dirname(mx.__file__))
    feats = runtime.Features() if hasattr(runtime, "Features") else None
    if feats is not None:
        enabled = [str(f) for f in getattr(feats, "enabled", lambda: [])()] \
            if callable(getattr(feats, "enabled", None)) else []
        if enabled:
            print("Features     :", ", ".join(enabled))
    import jax
    import jaxlib
    print("jax          :", jax.__version__)
    print("jaxlib       :", jaxlib.__version__)
    from .. import envs as _envs
    declared = _envs.snapshot()
    knobs = {k: v for k, v in os.environ.items()
             if k.startswith(("MXNET_", "JAX_", "XLA_"))}
    for k in sorted(knobs):
        # a set-but-undeclared MXNET_* is almost always a typo'd
        # knob nothing will ever read — this table is where the
        # operator finds out, so it must not be hidden
        tag = "" if not k.startswith("MXNET_") or k in declared \
            else "  (undeclared — typo? see mxnet_tpu/envs.py)"
        print("env %-24s: %s%s" % (k, knobs[k], tag))


def diagnose_backend(timeout):
    """Accelerator reachability (the zero-egress analogue of the
    reference's URL tests): jax.devices() in a subprocess so a hung
    backend cannot hang the diagnosis."""
    print("----------Backend Reachability----------")
    code = ("import jax; d = jax.devices(); "
            "print([(x.platform, x.device_kind) for x in d])")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout)
        if out.returncode == 0:
            print("devices      :", out.stdout.strip().splitlines()[-1])
        else:
            print("backend ERROR:", (out.stderr or "").strip()[-400:])
    except subprocess.TimeoutExpired:
        print("backend HUNG : jax.devices() did not answer within "
              "%ds — accelerator attachment is broken" % timeout)


# ---------------------------------------------------------------------------
# telemetry JSONL mode
# ---------------------------------------------------------------------------

def read_telemetry(path):
    """Parse a mxnet_tpu.telemetry JSONL sink. Unparseable lines —
    including a truncated final line from a run killed mid-append, or
    a line whose JSON prefix parses to a non-record scalar — are
    counted into ``skipped_lines`` and skipped, never fatal: the
    report renders everything else and warns once. A sink holding
    several runs (consecutive fits appending to the same
    MXNET_TELEMETRY_FILE) yields the LAST run."""
    out = {"run": None, "steps": [], "memory": [], "compiles": [],
           "utilization": [], "checkpoints": [], "serving": [],
           "decode": [], "router": [], "prefix_cache": [],
           "bucketing": [], "alerts": [], "usage": [],
           "usage_records": [],
           "loss_scale": [], "breakdown": None, "summary": None}
    skipped = 0
    unknown = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if not isinstance(rec, dict):
                # a kill mid-append can strand a prefix that is
                # itself valid JSON (a bare number, null) — still
                # not a record
                skipped += 1
                continue
            kind = rec.get("type")
            if kind == "run_start":
                out = {"run": rec, "steps": [], "memory": [],
                       "compiles": [], "utilization": [],
                       "checkpoints": [], "serving": [],
                       "decode": [], "router": [],
                       "prefix_cache": [], "bucketing": [],
                       "alerts": [], "usage": [],
                       "usage_records": [], "loss_scale": [],
                       "breakdown": None, "summary": None}
                skipped = 0     # earlier runs' damage is not THIS
                                # run's — the warning describes the
                                # run being rendered
                unknown = {}
            elif kind == "step":
                out["steps"].append(rec)
            elif kind == "memory":
                out["memory"].append(rec)
            elif kind == "memory_breakdown":
                out["breakdown"] = rec      # watermarks: last is max
            elif kind == "compile":
                out["compiles"].append(rec)
            elif kind == "utilization":
                out["utilization"].append(rec)
            elif kind == "checkpoint":
                out["checkpoints"].append(rec)
            elif kind == "serving":
                out["serving"].append(rec)
            elif kind == "decode":
                out["decode"].append(rec)
            elif kind == "router":
                out["router"].append(rec)
            elif kind == "prefix_cache":
                out["prefix_cache"].append(rec)
            elif kind == "bucketing":
                out["bucketing"].append(rec)
            elif kind == "alert":
                out["alerts"].append(rec)
            elif kind == "loss_scale":
                out["loss_scale"].append(rec)
            elif kind == "usage":
                out["usage"].append(rec)
            elif kind == "usage_record":
                # one closed per-request ledger line (the
                # MXNET_METER_FILE format) — diagnose pointed straight
                # at a ledger renders the bill from these
                out["usage_records"].append(rec)
            elif kind == "summary":
                out["summary"] = rec
            else:
                # a record kind this diagnose does not know — written
                # by a NEWER sink. Count it per kind instead of
                # dropping it silently, so a version skew between
                # producer and reader is visible in the report.
                key = kind if isinstance(kind, str) else "?"
                unknown[key] = unknown.get(key, 0) + 1
    out["skipped_lines"] = skipped
    out["unknown_kinds"] = unknown
    return out


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return "%.1f %s" % (n, unit)
        n /= 1024.0


def _fmt_flops(n):
    for unit in ("FLOP", "KFLOP", "MFLOP", "GFLOP", "TFLOP"):
        if abs(n) < 1000.0 or unit == "TFLOP":
            return "%.2f %s" % (n, unit)
        n /= 1000.0


def format_telemetry(tel):
    """Render the parsed telemetry run as the human tables (step-time
    percentiles over ALL step records in the file, phases, goodput,
    memory watermarks, per-key comms)."""
    from ..telemetry import percentile
    run = tel.get("run") or {}
    summary = tel.get("summary") or {}
    steps = tel.get("steps") or []
    lines = ["----------Telemetry Run----------",
             "run_id       : %s" % (run.get("run_id") or
                                    summary.get("run_id") or "?")]
    if run.get("meta"):
        lines.append("meta         : %s" % json.dumps(run["meta"]))
    if tel.get("skipped_lines"):
        lines.append("WARNING      : skipped %d unparseable line(s) — "
                     "a killed run strands at most one truncated "
                     "trailing record; the rest renders below"
                     % tel["skipped_lines"])
    if tel.get("unknown_kinds"):
        unk = tel["unknown_kinds"]
        lines.append("WARNING      : ignored %d record(s) of unknown "
                     "kind (%s) — the sink was written by a newer "
                     "mxnet_tpu than this diagnose understands; "
                     "everything else renders below"
                     % (sum(unk.values()),
                        ", ".join("%s x%d" % kv
                                  for kv in sorted(unk.items()))))

    compiles = tel.get("compiles") or []
    lines.append("----------Step time----------")
    durs = [s["dur_ms"] for s in steps if s.get("dur_ms") is not None]
    if durs:
        lines.append("steps        : %d" % len(durs))
        lines.append("mean(ms)     : %.3f" % (sum(durs) / len(durs)))
        for q in (50, 90, 99):
            lines.append("p%-2d(ms)      : %.3f" % (q,
                                                    percentile(durs, q)))
        lines.append("max(ms)      : %.3f" % max(durs))
    elif compiles:
        # a sink with compiles but no steps is not a broken file — the
        # run crashed before step 1, or was a compile-only run
        lines.append("no step records — run recorded %d compile(s) "
                     "but no steps (crashed before step 1, or a "
                     "compile-only run)" % len(compiles))
    else:
        lines.append("no step records")

    # the summary's totals are whole-run truth (they include phases
    # that run BETWEEN steps — epoch-end checkpoint/eval); summing the
    # step records is the fallback for a run that died before stop()
    totals = dict(summary.get("phases_ms") or {})
    if not totals:
        for s in steps:
            for phase, ms in (s.get("phases_ms") or {}).items():
                totals[phase] = totals.get(phase, 0.0) + ms
    if totals:
        lines.append("----------Phases----------")
        whole = sum(totals.values()) or 1.0
        for phase in sorted(totals, key=totals.get, reverse=True):
            lines.append("%-12s : %12.3f ms  (%5.1f%%)"
                         % (phase, totals[phase],
                            100.0 * totals[phase] / whole))

    # -- compile log (mxnet_tpu.compile_watch) --------------------------
    sum_compile = summary.get("compile") or {}
    if compiles or sum_compile:
        lines.append("----------Compilation----------")
        progs = {}
        for c in compiles:
            p = progs.setdefault(c.get("program", "?"),
                                 {"count": 0, "ms": 0.0, "causes": {},
                                  "churn": {}})
            p["count"] += 1
            p["ms"] += c.get("dur_ms", 0.0)
            cause = (c.get("cause") or "?").split(" ", 1)[0]
            p["causes"][cause] = p["causes"].get(cause, 0) + 1
            for arg in c.get("changed", ()):
                p["churn"][arg] = p["churn"].get(arg, 0) + 1
        if not progs:
            # compile records flushed out of an earlier file segment:
            # fall back to the summary's per-program table
            for name, s in (sum_compile.get("programs") or {}).items():
                progs[name] = {"count": s.get("count", 0),
                               "ms": s.get("total_s", 0.0) * 1e3,
                               "causes": dict(s.get("causes") or {}),
                               "churn": dict(s.get("churn") or {})}
        total_ms = 0.0
        lines.append("%-28s %6s %10s  %s"
                     % ("program", "count", "time(ms)",
                        "causes [churning arg]"))
        for name in sorted(progs, key=lambda n: -progs[n]["ms"]):
            p = progs[name]
            total_ms += p["ms"]
            causes = ",".join("%s:%d" % kv
                              for kv in sorted(p["causes"].items()))
            if p["churn"]:
                causes += " [%s]" % max(p["churn"], key=p["churn"].get)
            lines.append("%-28s %6d %10.1f  %s"
                         % (name[:28], p["count"], p["ms"], causes))
        lines.append("%-28s %6d %10.1f" % (
            "TOTAL", sum(p["count"] for p in progs.values()), total_ms))
        for s in sum_compile.get("storms") or []:
            lines.append("RECOMPILE STORM: %s compiled %sx within %s "
                         "steps — churning argument '%s'"
                         % (s.get("program"), s.get("compiles"),
                            s.get("window_steps"), s.get("arg")))
        fused = {k: v for k, v in (summary.get("counters") or {}).items()
                 if k.startswith("fused_step")}
        if fused:
            lines.append("fused-step cache: " + ", ".join(
                "%s=%s" % (k[len("fused_step_"):],
                           round(v, 1) if isinstance(v, float) else v)
                for k, v in sorted(fused.items())))
        cache = sum_compile.get("cache") or {}
        if cache:
            lines.append(
                "compile-cache: %d hit(s) / %d miss(es), "
                "%s read / %s written, %d entr%s (%s on disk), "
                "%d evicted, %d error(s)"
                % (cache.get("hits", 0), cache.get("misses", 0),
                   _fmt_bytes(cache.get("bytes_read", 0)),
                   _fmt_bytes(cache.get("bytes_written", 0)),
                   cache.get("entries", 0),
                   "y" if cache.get("entries", 0) == 1 else "ies",
                   _fmt_bytes(cache.get("size_bytes", 0)),
                   cache.get("evictions", 0), cache.get("errors", 0)))

    # -- hardware utilization (MFU / memory bandwidth) ------------------
    utils = tel.get("utilization") or []
    sum_util = summary.get("utilization") or {}
    if utils or sum_util:
        lines.append("----------Utilization----------")
        if sum_util.get("device_kind"):
            lines.append("device       : %s x%d (peak %.1f TFLOP/s, "
                         "%.0f GB/s each)"
                         % (sum_util["device_kind"],
                            sum_util.get("n_devices", 1),
                            sum_util.get("peak_flops", 0.0) / 1e12,
                            sum_util.get("peak_bw", 0.0) / 1e9))
        mfus = [u["mfu"] for u in utils if u.get("mfu") is not None]
        if not mfus and sum_util.get("mfu"):
            m = sum_util["mfu"]
            lines.append("MFU p50      : %8.3f %%" % (100 * m["p50"]))
            lines.append("MFU p90      : %8.3f %%" % (100 * m["p90"]))
        elif mfus:
            lines.append("MFU p50      : %8.3f %%"
                         % (100 * percentile(mfus, 50)))
            lines.append("MFU p90      : %8.3f %%"
                         % (100 * percentile(mfus, 90)))
        bwus = [u["bw_util"] for u in utils
                if u.get("bw_util") is not None]
        if bwus:
            lines.append("HBM BW p50   : %8.3f %%"
                         % (100 * percentile(bwus, 50)))
        flops = [u.get("flops", 0.0) for u in utils]
        fdurs = [u.get("dur_ms") for u in utils
                 if u.get("dur_ms") and u.get("flops")]
        if any(flops):
            lines.append("flops/step   : %s (dispatched, XLA cost "
                         "model)" % _fmt_flops(
                             sum(flops) / max(1, len(flops))))
            if fdurs:
                tf = sum(u["flops"] for u in utils
                         if u.get("dur_ms") and u.get("flops"))
                lines.append("sustained    : %s/s"
                             % _fmt_flops(tf / (sum(fdurs) / 1e3)))

    # -- checkpoint saves (mxnet_tpu.checkpoint) ------------------------
    ckpts = tel.get("checkpoints") or []
    sum_ckpt = summary.get("checkpoint") or {}
    if ckpts or sum_ckpt:
        lines.append("----------Checkpoints----------")
        lines.append("%5s %4s %12s %10s %10s %10s %7s"
                     % ("epoch", "ok", "bytes", "total(ms)",
                        "block(ms)", "async(ms)", "shards"))
        for c in ckpts:
            lines.append("%5s %4s %12d %10.1f %10.1f %10.1f %7s"
                         % (c.get("epoch", "?"),
                            "yes" if c.get("ok") else "NO",
                            c.get("bytes", 0) or 0,
                            c.get("total_ms", 0.0) or 0.0,
                            c.get("blocking_ms", 0.0) or 0.0,
                            c.get("async_ms", 0.0) or 0.0,
                            c.get("shards", "-")))
        blocking = sum_ckpt.get("blocking_ms") if sum_ckpt else None
        if blocking is None:
            blocking = sum(c.get("blocking_ms", 0.0) or 0.0
                           for c in ckpts)
        async_ms = sum_ckpt.get("async_ms") if sum_ckpt else None
        if async_ms is None:
            async_ms = sum(c.get("async_ms", 0.0) or 0.0 for c in ckpts)
        total = blocking + async_ms
        if total > 0:
            lines.append("async share  : %.1f%% of %.1f ms save work "
                         "ran off the training thread (blocking "
                         "%.1f ms)" % (100.0 * async_ms / total, total,
                                       blocking))
        failures = sum_ckpt.get("failures",
                                sum(1 for c in ckpts
                                    if not c.get("ok")))
        if failures:
            lines.append("failed saves : %d (training continued; the "
                         "previous good epoch stays the resume point)"
                         % failures)
        last_good = sum_ckpt.get("last_good_epoch")
        if last_good is None and ckpts:
            last_good = ckpts[-1].get("last_good_epoch")
        lines.append("last good    : epoch %s" % (last_good
                                                  if last_good is not None
                                                  else "none"))

    # -- inference serving (mxnet_tpu.serving) --------------------------
    servings = tel.get("serving") or []
    # records are cumulative snapshots: the last one is the run's truth
    sv = servings[-1] if servings else (summary.get("serving") or {})
    if sv:
        lines.append("----------Serving----------")
        lines.append("requests     : %d submitted (completed %d, shed "
                     "%d, timeout %d, errors %d)"
                     % (sv.get("requests", 0), sv.get("completed", 0),
                        sv.get("shed", 0), sv.get("timeouts", 0),
                        sv.get("errors", 0)))
        lat = sv.get("latency_ms") or {}
        if lat:
            lines.append("latency(ms)  : p50 %.3f  p90 %.3f  p99 %.3f "
                         " max %.3f"
                         % (lat.get("p50", 0.0), lat.get("p90", 0.0),
                            lat.get("p99", 0.0), lat.get("max", 0.0)))
        lines.append("throughput   : %.2f req/s over %d batch(es)"
                     % (sv.get("rps", 0.0), sv.get("batches", 0)))
        occ = sv.get("occupancy")
        if occ is not None:
            from ..bucketing.ladder import bucket_sort_key
            per_bucket = " ".join(
                "b%s:%s" % kv
                for kv in sorted((sv.get("buckets") or {}).items(),
                                 key=lambda kv: bucket_sort_key(kv[0])))
            lines.append("occupancy    : %.1f%% mean of bucket slots "
                         "(%s)" % (100.0 * occ, per_bucket or "-"))
        lines.append("queue depth  : peak %d of bound %d (ladder %s)"
                     % (sv.get("queue_peak", 0),
                        sv.get("max_queue", 0),
                        sv.get("ladder", [])))
        rb = sv.get("replica_batches") or []
        if sv.get("replicas", 1) > 1:
            lines.append("replicas     : %d (batches per replica: %s — "
                         "least-outstanding dispatch)"
                         % (sv["replicas"],
                            ", ".join(str(b) for b in rb)))
        if sv.get("dispatch_faults"):
            lines.append("faults       : %d injected dispatch fault(s) "
                         "survived" % sv["dispatch_faults"])
        shed_pri = sv.get("shed_by_priority") or {}
        if shed_pri:
            lines.append("shed/prio    : %s (lowest class sheds "
                         "first)"
                         % " ".join("p%s:%s" % kv_
                                    for kv_ in sorted(
                                        shed_pri.items())))

    # -- dynamic loss scale (fault.scale_backoff under AMP) --------------
    ls_recs = tel.get("loss_scale") or []
    if ls_recs:
        lines.append("----------Loss Scale----------")
        shown = ls_recs[-12:]
        traj = "%g" % shown[0].get("prev", 0)
        for r in shown:
            traj += " -> %g (%s)" % (r.get("scale", 0),
                                     r.get("cause") or "?")
        prefix = "(+%d earlier) " % (len(ls_recs) - len(shown)) \
            if len(ls_recs) > len(shown) else ""
        lines.append("trajectory   : %s%s" % (prefix, traj))
        n_back = sum(1 for r in ls_recs
                     if r.get("cause") == "backoff")
        lines.append("changes      : %d backoff(s), %d regrow(s); "
                     "final scale %g — a scale pinned at 1.0 means a "
                     "numerics problem, not an overflow problem"
                     % (n_back, len(ls_recs) - n_back,
                        ls_recs[-1].get("scale", 0)))

    # -- autoregressive decode serving (serving.decode) -----------------
    dec_recs = tel.get("decode") or []
    # records are cumulative per server name: keep each name's last
    dec = {}
    for rec in dec_recs:
        dec[rec.get("name") or "default"] = rec
    if not dec:
        dec = dict(summary.get("decode") or {})
    if dec:
        lines.append("----------Decode----------")
        for name in sorted(dec):
            d = dec[name]
            lines.append("%-12s : %d request(s) (completed %d, "
                         "cancelled %d, timeout %d, shed %d, "
                         "preempted %d, errors %d)"
                         % (name[:12], d.get("requests", 0),
                            d.get("completed", 0),
                            d.get("cancelled", 0),
                            d.get("timeouts", 0), d.get("shed", 0),
                            d.get("preempted", 0), d.get("errors", 0)))
            frac = d.get("prefill_fraction")
            lines.append("  steps      : %d prefill + %d decode (%s "
                         "prefill share) — the continuous-batching "
                         "mix"
                         % (d.get("prefill_steps", 0),
                            d.get("decode_steps", 0),
                            "%.1f%%" % (100.0 * frac)
                            if frac is not None else "n/a"))
            lines.append("  tokens     : %d out at %.1f tokens/s"
                         % (d.get("tokens_out", 0),
                            d.get("tokens_per_sec", 0.0)))
            it = d.get("inter_token_ms") or {}
            if it:
                lines.append("  inter-token: p50 %.3f ms  p99 %.3f ms "
                             " max %.3f ms"
                             % (it.get("p50", 0.0), it.get("p99", 0.0),
                                it.get("max", 0.0)))
            tt = d.get("ttft_ms") or {}
            if tt:
                lines.append("  first token: p50 %.3f ms  p99 %.3f ms"
                             % (tt.get("p50", 0.0), tt.get("p99", 0.0)))
            kv = d.get("kv") or {}
            if kv:
                pages = kv.get("pages", 0) or 1
                dtype = kv.get("dtype") or "float32"
                lines.append("  kv pool    : %d/%d pages used (peak "
                             "%d, %.1f%%), %d evicted, page size %d, "
                             "dtype %s"
                             % (kv.get("used", 0), kv.get("pages", 0),
                                kv.get("peak_used", 0),
                                100.0 * kv.get("peak_used", 0) / pages,
                                kv.get("evicted", 0),
                                kv.get("page_size", 0), dtype))
            if d.get("swaps"):
                lines.append("  weights    : %d hot swap(s), serving "
                             "version %s (%d generation(s) alive)"
                             % (d.get("swaps", 0),
                                d.get("weight_version", "?"),
                                d.get("versions_alive", 1)))
            shed_pri = d.get("shed_by_priority") or {}
            if shed_pri:
                lines.append("  shed/prio  : %s"
                             % " ".join("p%s:%s" % kv_
                                        for kv_ in sorted(
                                            shed_pri.items())))

    # -- KV prefix cache (serving.kvcache page sharing) -----------------
    px_recs = tel.get("prefix_cache") or []
    # records are cumulative per server name: keep each name's last
    px = {}
    for rec in px_recs:
        px[rec.get("name") or "default"] = rec
    if not px:
        px = dict(summary.get("prefix_cache") or {})
    if px:
        lines.append("----------Prefix cache----------")
        for name in sorted(px):
            p = px[name]
            hits = p.get("hits", 0)
            total = hits + p.get("misses", 0)
            lines.append("%-12s : %d/%d prompt(s) hit (%.1f%%), %d "
                         "token(s) served from shared pages"
                         % (name[:12], hits, total,
                            100.0 * p.get("hit_rate", 0.0),
                            p.get("hit_tokens", 0)))
            lines.append("  saved      : %s of prefill K/V not "
                         "recomputed"
                         % _fmt_bytes(p.get("bytes_saved", 0)))
            pool = p.get("pool") or {}
            lines.append("  pages      : %d indexed, %d shared now, "
                         "%d cow split(s) (%d degraded), %d cold "
                         "entr(ies) evicted"
                         % (pool.get("entries", 0),
                            pool.get("shared_pages",
                                     p.get("shared_pages", 0)),
                            p.get("cow_splits", 0),
                            p.get("cow_degraded", 0),
                            pool.get("evicted", 0)))
            owners = p.get("owners") or {}
            for oname in sorted(owners):
                o = owners[oname]
                quota = o.get("quota")
                lines.append("  model %-6s: %d page(s) held%s, pool "
                             "priority %d"
                             % (oname[:6], o.get("used", 0),
                                " of %d quota" % quota
                                if quota else "",
                                o.get("priority", 0)))

    # -- fleet serving router (serving.router) --------------------------
    rt_recs = tel.get("router") or []
    # records are cumulative per router name: keep each name's last
    rt = {}
    for rec in rt_recs:
        rt[rec.get("name") or "default"] = rec
    if not rt:
        rt = dict(summary.get("router") or {})
    if rt:
        lines.append("----------Router----------")
        for name in sorted(rt):
            r = rt[name]
            lines.append("%-12s : %d session(s) (dispatched %d, "
                         "completed %d, failed %d, cancelled %d, "
                         "shed %d, timeout %d)"
                         % (name[:12], r.get("requests", 0),
                            r.get("dispatched", 0),
                            r.get("completed", 0), r.get("failed", 0),
                            r.get("cancelled", 0), r.get("shed", 0),
                            r.get("timeouts", 0)))
            reps = r.get("replicas") or []
            if reps:
                lines.append("  replicas   : %d up of %d — %s"
                             % (r.get("replicas_up", 0), len(reps),
                                " ".join(
                                    "%s:%s(out %s)"
                                    % (p.get("name", "?"),
                                       p.get("state", "?"),
                                       p.get("outstanding", 0))
                                    for p in reps)))
            lines.append("  failover   : %d replica(s) lost, %d "
                         "session(s) re-homed, %d token(s) replayed "
                         "by re-prefill%s"
                         % (r.get("replicas_lost", 0),
                            r.get("failovers", 0),
                            r.get("replay_tokens", 0),
                            " (%d from shared prefix pages)"
                            % r.get("replay_cached_tokens", 0)
                            if r.get("replay_cached_tokens") else ""))
            res = r.get("failover_resume_ms") or {}
            if res:
                lines.append("  resume     : p50 %.3f ms  p99 %.3f ms "
                             " max %.3f ms (loss detection -> first "
                             "resumed token)"
                             % (res.get("p50", 0.0),
                                res.get("p99", 0.0),
                                res.get("max", 0.0)))
            if r.get("drains") or r.get("drain_timeouts"):
                lines.append("  drains     : %d graceful (%d timed "
                             "out into failover)"
                             % (r.get("drains", 0),
                                r.get("drain_timeouts", 0)))
            for tname in sorted(r.get("tenants") or {}):
                t = (r.get("tenants") or {})[tname]
                lat = t.get("latency_ms") or {}
                lines.append("  tenant %-5s: w=%s rate=%s — %d "
                             "submitted, %d done, %d shed, %d "
                             "throttle(s)%s"
                             % (tname[:5], t.get("weight", 1.0),
                                t.get("rate", 0.0) or "inf",
                                t.get("submitted", 0),
                                t.get("completed", 0),
                                t.get("shed", 0),
                                t.get("throttled", 0),
                                ", p99 %.1f ms" % lat["p99"]
                                if lat else ""))
            if r.get("scale_up_signals") or r.get("scale_down_signals"):
                lines.append("  autoscale  : %d scale-up signal(s), "
                             "%d scale-down"
                             % (r.get("scale_up_signals", 0),
                                r.get("scale_down_signals", 0)))

    # -- usage metering & cost attribution (mxnet_tpu.metering) ---------
    usage = _usage_view(tel, summary)
    if usage:
        router_rec = next(iter(rt.values())) if len(rt) == 1 else None
        lines.append("----------Usage----------")
        for mname in sorted(usage):
            u = usage[mname]
            lines.append("%-12s : %d request(s) metered (closed %d, "
                         "open %d%s)%s"
                         % (mname[:12], u.get("admitted", 0),
                            u.get("closed", 0), u.get("open", 0),
                            ", dispatched %d" % u["dispatched"]
                            if u.get("dispatched") is not None else "",
                            " — synthesized from raw ledger lines"
                            if u.get("synthesized") else ""))
            for tname in sorted(u.get("tenants") or {}):
                t = (u.get("tenants") or {})[tname]
                ocs = " ".join("%s:%d" % kv for kv in
                               sorted((t.get("outcomes") or {})
                                      .items()))
                lines.append("  tenant %-5s: %d+%d tok (prompt+gen), "
                             "%s, %.3f page*s, %d tok credited, %d "
                             "replayed%s"
                             % (tname[:5],
                                t.get("prompt_tokens", 0),
                                t.get("generated_tokens", 0),
                                _fmt_flops(t.get("flops", 0) or 0),
                                t.get("page_seconds", 0) or 0,
                                t.get("prefix_hit_tokens", 0),
                                t.get("replay_tokens", 0),
                                " — " + ocs if ocs else ""))
            train = u.get("training")
            if train:
                goodput = train.get("goodput")
                lines.append("  training   : %d step(s), %.3f "
                             "device*s%s%s"
                             % (train.get("steps", 0),
                                train.get("device_seconds", 0.0),
                                ", %s total"
                                % _fmt_flops(train["total_flops"])
                                if train.get("total_flops") else "",
                                ", goodput %.1f%% (%d wasted -> "
                                "effective %.3f device*s)"
                                % (100.0 * goodput,
                                   train.get("wasted_steps", 0),
                                   train.get(
                                       "effective_device_seconds",
                                       0.0))
                                if goodput is not None else ""))
            ledger = u.get("ledger")
            if isinstance(ledger, dict) and ledger.get("path"):
                lines.append("  ledger     : %d record(s) -> %s "
                             "(%d write error(s))"
                             % (ledger.get("written", 0),
                                ledger.get("path"),
                                ledger.get("errors", 0)))
            checks = _usage_checks(u, router_rec)
            if checks:
                ok = all(c[3] for c in checks)
                bad = ["%s (%s != %s)" % (c[0], c[1], c[2])
                       for c in checks if not c[3]]
                lines.append("  reconcile  : %d/%d conservation "
                             "check(s) hold (dual-entry books + "
                             "router counters)%s  [%s]"
                             % (sum(1 for c in checks if c[3]),
                                len(checks),
                                " — " + ", ".join(bad) if bad else "",
                                "OK" if ok else "MISMATCH"))

    # -- SLO watchdog alerts (mxnet_tpu.livemetrics) --------------------
    alerts = tel.get("alerts") or []
    if not alerts and summary.get("alerts"):
        alerts = summary["alerts"]
    if alerts:
        lines.append("----------Alerts----------")
        lines.append("%6s %-20s %s" % ("step", "kind", "detail"))
        for a in alerts:
            lines.append("%6s %-20s %s"
                         % (a.get("seq", "-"),
                            (a.get("kind") or "?")[:20],
                            a.get("message", "")))
        lines.append("%d alert(s) fired by the SLO watchdog "
                     "(MXNET_WATCHDOG=1; thresholds via "
                     "MXNET_WATCHDOG_* envs)" % len(alerts))

    # -- shape bucketing (mxnet_tpu.bucketing) --------------------------
    buck_recs = tel.get("bucketing") or []
    # records are cumulative per producer name: keep each name's last
    buck = {}
    for rec in buck_recs:
        buck[rec.get("name") or "default"] = rec
    if not buck:
        buck = dict(summary.get("bucketing") or {})
    if buck:
        lines.append("----------Bucketing----------")
        for name in sorted(buck):
            b = buck[name]
            from ..bucketing.ladder import bucket_sort_key
            per_bucket = " ".join(
                "b%s:%s" % kv
                for kv in sorted((b.get("buckets") or {}).items(),
                                 key=lambda kv: bucket_sort_key(kv[0])))
            lines.append("%-12s : %d batch(es) over %d bucket(s) (%s)"
                         % (name[:12], b.get("batches", 0),
                            len(b.get("buckets") or {}),
                            per_bucket or "-"))
            share = b.get("padding_share")
            lines.append("  padding    : %s of padded-batch elements "
                         "were padding (pad rows %d)"
                         % ("%.1f%%" % (100.0 * share)
                            if share is not None else "n/a",
                            b.get("pad_rows", 0)))
            rtf = b.get("real_token_fraction")
            if rtf is not None:
                lines.append("  real tokens: %.1f%% of emitted "
                             "elements were real work (the packing-"
                             "efficiency figure)" % (100.0 * rtf))
            lines.append("  samples    : %d bucketed, %d discarded "
                         "(longer than the ladder top)"
                         % (b.get("samples", 0), b.get("discarded", 0)))

    lines.append("----------Goodput----------")
    skipped = sum(s.get("skipped", 0) for s in steps)
    retried = sum(s.get("retries", 0) for s in steps)
    samples = sum(s.get("samples", 0) for s in steps)
    n = len(steps)
    productive = n - skipped
    lines.append("steps        : %d (productive %d, skipped %d, "
                 "retried ops %d)" % (n, productive, skipped, retried))
    if n:
        lines.append("goodput      : %.1f%%" % (100.0 * productive / n))
    events = summary.get("events") or {}
    gen = events.get("supervisor_restart_generation")
    if gen:
        # reconcile the supervisor's restart-the-world count with the
        # resume accounting fault.stats() carries: a supervised
        # restart that found a clean manifest resumes cleanly; one
        # that rolled past torn epochs shows up in the rollback
        # counters below
        fstats = summary.get("fault") or {}
        lines.append("restarts     : supervisor restart generation %d "
                     "(resumes this run: %d clean, %d rollback)"
                     % (gen, fstats.get("clean_resumes", 0),
                        fstats.get("rollback_resumes", 0)))
    rollback = events.get("resume_rollback_epochs")
    if rollback:
        # reconcile lost work with the rollback the resume scan took:
        # steps/epoch comes from the run itself. The meta begin_epoch
        # predates the resume bump, so prefer the resume_next_epoch
        # event (the epoch training actually restarted from)
        meta = run.get("meta") or {}
        begin = events.get("resume_next_epoch",
                           meta.get("begin_epoch"))
        lost = ""
        if n and meta.get("num_epoch") is not None \
                and begin is not None:
            epochs_run = max(int(meta["num_epoch"]) - int(begin), 1)
            lost = " (~%d steps of lost work re-trained)" \
                % (rollback * (n // epochs_run))
        lines.append("rollback     : resume skipped %d corrupt newer "
                     "epoch(s)%s" % (rollback, lost))
    if samples and durs:
        lines.append("samples/sec  : %.2f"
                     % (samples / (sum(durs) / 1e3)))
    if summary.get("fault"):
        lines.append("fault.stats  : %s" % json.dumps(summary["fault"]))
    if summary.get("events"):
        # free-form telemetry.note() events — e.g.
        # fused_step_eager_monitor explains "why was this run eager"
        lines.append("events       : %s" % json.dumps(summary["events"]))

    lines.append("----------Memory----------")
    watermarks = {}
    for m in tel.get("memory") or []:
        dev = m.get("device", "?")
        peak = max(int(m.get("peak_bytes_in_use", 0) or 0),
                   int(m.get("bytes_in_use", 0) or 0))
        watermarks[dev] = max(watermarks.get(dev, 0), peak)
    if not watermarks and summary.get("memory"):
        watermarks = {d: w.get("peak_bytes_in_use", 0)
                      for d, w in summary["memory"].items()}
    if watermarks:
        for dev in sorted(watermarks):
            lines.append("%-24s peak %s"
                         % (dev, _fmt_bytes(watermarks[dev])))
    else:
        lines.append("no memory samples (backend without memory_stats)")
    breakdown = summary.get("memory_breakdown") or tel.get("breakdown")
    if breakdown:
        # the FSDP/ZeRO split: how much of each device's residency is
        # a 1/N shard vs a full replica — the observable form of the
        # "params drop to 1/N" claim, per run
        total = sum(int(breakdown.get(k, 0) or 0)
                    for k in ("params_sharded", "params_replicated",
                              "opt_state"))
        for key, label in (("params_sharded", "params sharded (1/N)"),
                           ("params_replicated", "params replicated"),
                           ("opt_state", "optimizer state")):
            b = int(breakdown.get(key, 0) or 0)
            share = (100.0 * b / total) if total else 0.0
            lines.append("%-24s %12s  (%5.1f%%) per device"
                         % (label, _fmt_bytes(b), share))

    all_comms = summary.get("comms") or {}
    h2d = {k: v for k, v in all_comms.items() if k.startswith("h2d:")}
    sync = {k: v for k, v in all_comms.items()
            if k.startswith("grad_sync:")}
    links = {k: v for k, v in all_comms.items()
             if k.startswith(("ici:", "dcn:"))}
    comms = {k: v for k, v in all_comms.items()
             if not k.startswith(("h2d:", "grad_sync:", "ici:",
                                  "dcn:"))}

    if sync:
        # the bucketed gradient exchange (parallel.grad_sync): one row
        # per bucket. In-program buckets (reduce-scatter scheduled by
        # XLA inside the step) carry bytes but no host-observable
        # latency; eager kvstore buckets carry both.
        lines.append("----------Gradient sync----------")
        lines.append("%-24s %8s %12s %12s" % ("bucket", "steps",
                                              "bytes", "time(ms)"))
        tot_b = tot_ms = 0.0
        for key in sorted(sync):
            c = sync[key]
            tot_b += c.get("bytes", 0)
            tot_ms += c.get("time_ms", 0.0)
            lines.append("%-24s %8d %12d %12.3f"
                         % (key[len("grad_sync:"):], c.get("calls", 0),
                            c.get("bytes", 0), c.get("time_ms", 0.0)))
        lines.append("%-24s %8s %12d %12.3f" % ("TOTAL", "", tot_b,
                                                tot_ms))
        whole = sum(totals.values()) or 1.0
        share = 100.0 * totals.get("sync", 0.0) / whole
        steps_synced = (summary.get("events") or {}).get(
            "grad_sync_steps")
        if steps_synced:
            lines.append("in-program   : %d step(s) synced inside the "
                         "compiled step (overlapped with backward — "
                         "no host sync phase)" % steps_synced)
        lines.append("sync share   : %.1f%% of accounted phase time "
                     "(%d bucket(s)/step)" % (share, len(sync)))

    if links:
        # the mesh-layout audit: how much of each collective kind's
        # combine traffic rides the intra-host fast link (ici) vs the
        # cross-host link (dcn) under mesh.link_split's hop model — a
        # data axis split on host boundaries shows dcn ONLY here
        lines.append("----------Per-link comms (ici vs dcn)----------")
        lines.append("%-24s %8s %14s %14s %7s"
                     % ("collective", "calls", "ici bytes",
                        "dcn bytes", "dcn%"))
        kinds = sorted({k.split(":", 1)[1] for k in links})
        tot_i = tot_d = 0
        for kind in kinds:
            ici = links.get("ici:%s" % kind) or {}
            dcn = links.get("dcn:%s" % kind) or {}
            bi, bd = ici.get("bytes", 0), dcn.get("bytes", 0)
            tot_i += bi
            tot_d += bd
            calls = max(ici.get("calls", 0), dcn.get("calls", 0))
            share = 100.0 * bd / (bi + bd) if (bi + bd) else 0.0
            lines.append("%-24s %8d %14d %14d %6.1f%%"
                         % (kind[:24], calls, bi, bd, share))
        tot_share = 100.0 * tot_d / (tot_i + tot_d) \
            if (tot_i + tot_d) else 0.0
        lines.append("%-24s %8s %14d %14d %6.1f%%"
                     % ("TOTAL", "", tot_i, tot_d, tot_share))

    lines.append("----------Comms----------")
    if comms:
        lines.append("%-24s %8s %12s %12s" % ("kind:key", "calls",
                                              "bytes", "time(ms)"))
        for key in sorted(comms):
            c = comms[key]
            lines.append("%-24s %8d %12d %12.3f"
                         % (key, c.get("calls", 0), c.get("bytes", 0),
                            c.get("time_ms", 0.0)))
    else:
        lines.append("no comms records (run had no kvstore/collectives "
                     "or no summary record)")

    if h2d:
        # the input pipeline's device-prefetch transfers run on the
        # placer thread: comparing their total time with the data_wait
        # phase shows how much H2D was hidden behind compute
        lines.append("----------H2D transfer (input pipeline)----------")
        lines.append("%-24s %8s %12s %12s" % ("key", "copies", "bytes",
                                              "time(ms)"))
        tot_ms = tot_b = 0.0
        for key in sorted(h2d):
            c = h2d[key]
            tot_ms += c.get("time_ms", 0.0)
            tot_b += c.get("bytes", 0)
            lines.append("%-24s %8d %12d %12.3f"
                         % (key[len("h2d:"):], c.get("calls", 0),
                            c.get("bytes", 0), c.get("time_ms", 0.0)))
        lines.append("%-24s %8s %12d %12.3f" % ("TOTAL", "", tot_b,
                                                tot_ms))
        wait_ms = totals.get("data_wait", 0.0)
        lines.append("h2d placement ran on the prefetch thread, off "
                     "the step critical path (%.3f ms); consumer "
                     "data_wait (queue-dry stalls only) was %.3f ms"
                     % (tot_ms, wait_ms))
    return "\n".join(lines)


def _last_by_name(recs, fallback):
    """Cumulative-snapshot record streams (serving/decode/router/
    bucketing): the last record per name is the truth."""
    by = {}
    for rec in recs or []:
        by[rec.get("name") or "default"] = rec
    if not by and fallback:
        by = dict(fallback)
    return by or None


_USAGE_SUM_FIELDS = ("prompt_tokens", "generated_tokens",
                     "replay_tokens", "replay_cached_tokens", "flops",
                     "page_seconds", "prefix_hit_tokens",
                     "prefix_bytes_saved", "queue_ms", "failovers")


def _usage_view(tel, summary):
    """Latest ``usage`` meter snapshot per name (falling back to the
    summary block) — or, when diagnose is pointed straight at a
    ``MXNET_METER_FILE`` ledger, one snapshot synthesized from its
    raw per-request ``usage_record`` lines."""
    us = _last_by_name(tel.get("usage"), (summary or {}).get("usage"))
    if us:
        return us
    recs = tel.get("usage_records") or []
    if not recs:
        return None
    tenants = {}
    outcomes = {}
    totals = {k: 0 for k in _USAGE_SUM_FIELDS}
    for r in recs:
        t = tenants.get(r.get("tenant") or "?")
        if t is None:
            t = tenants[r.get("tenant") or "?"] = dict(
                {k: 0 for k in _USAGE_SUM_FIELDS},
                outcomes={}, closed=0, open=0)
        for k in _USAGE_SUM_FIELDS:
            t[k] += r.get(k, 0) or 0
            totals[k] += r.get(k, 0) or 0
        oc = r.get("outcome") or "?"
        t["outcomes"][oc] = t["outcomes"].get(oc, 0) + 1
        outcomes[oc] = outcomes.get(oc, 0) + 1
        t["closed"] += 1
    return {"ledger": {
        "name": "ledger", "admitted": len(recs),
        "closed": len(recs), "open": 0, "dispatched": None,
        "tenants": tenants, "outcomes": outcomes, "totals": totals,
        "synthesized": True}}


def _usage_checks(u, router):
    """The conservation cross-checks for one meter snapshot:
    ``(name, lhs, rhs, ok)`` tuples — the meter's own dual-entry
    verdict plus its totals against the Router's independently
    incremented counters (when a router record is in the same
    sink)."""
    checks = []
    rc = u.get("reconcile") or {}
    if rc:
        checks.append(("books", "sum-over-tenants", "totals",
                       bool(rc.get("ok"))))
    if router and u.get("dispatched") is not None:
        tot = u.get("totals") or {}
        oc = u.get("outcomes") or {}
        failed_group = oc.get("timeout", 0) + oc.get("preempted", 0) \
            + oc.get("failed", 0)
        for name, lhs, rhs in (
                ("admitted", u.get("admitted"),
                 router.get("requests")),
                ("dispatched", u.get("dispatched"),
                 router.get("dispatched")),
                ("completed", oc.get("completed", 0),
                 router.get("completed")),
                ("cancelled", oc.get("cancelled", 0),
                 router.get("cancelled")),
                ("shed", oc.get("shed", 0), router.get("shed")),
                ("failed", failed_group, router.get("failed")),
                ("replay_tokens", tot.get("replay_tokens"),
                 router.get("replay_tokens")),
                ("replay_cached_tokens",
                 tot.get("replay_cached_tokens"),
                 router.get("replay_cached_tokens")),
                ("throttles", u.get("throttle_events"),
                 router.get("throttles"))):
            if rhs is None:
                continue
            checks.append((name, lhs, rhs, lhs == rhs))
    return checks


def telemetry_json(tel):
    """The ``--format json`` mirror of :func:`format_telemetry`: every
    table as one structured record — same aggregation, no layout."""
    from ..telemetry import percentile
    run = tel.get("run") or {}
    summary = tel.get("summary") or {}
    steps = tel.get("steps") or []
    durs = [s["dur_ms"] for s in steps if s.get("dur_ms") is not None]
    out = {"run_id": run.get("run_id") or summary.get("run_id"),
           "meta": run.get("meta") or None,
           "skipped_lines": tel.get("skipped_lines", 0),
           "unknown_kinds": tel.get("unknown_kinds") or {}}
    out["step_time"] = {
        "steps": len(durs),
        "mean_ms": sum(durs) / len(durs),
        "p50_ms": percentile(durs, 50),
        "p90_ms": percentile(durs, 90),
        "p99_ms": percentile(durs, 99),
        "max_ms": max(durs)} if durs else None
    totals = dict(summary.get("phases_ms") or {})
    if not totals:
        for s in steps:
            for phase, ms in (s.get("phases_ms") or {}).items():
                totals[phase] = totals.get(phase, 0.0) + ms
    out["phases_ms"] = totals or None
    # compilation: the same per-program fold format_telemetry renders
    compiles = tel.get("compiles") or []
    sum_compile = summary.get("compile") or {}
    progs = {}
    for c in compiles:
        p = progs.setdefault(c.get("program", "?"),
                             {"count": 0, "ms": 0.0, "causes": {},
                              "churn": {}})
        p["count"] += 1
        p["ms"] += c.get("dur_ms", 0.0)
        cause = (c.get("cause") or "?").split(" ", 1)[0]
        p["causes"][cause] = p["causes"].get(cause, 0) + 1
        for arg in c.get("changed", ()):
            p["churn"][arg] = p["churn"].get(arg, 0) + 1
    if not progs:
        for name, s in (sum_compile.get("programs") or {}).items():
            progs[name] = {"count": s.get("count", 0),
                           "ms": s.get("total_s", 0.0) * 1e3,
                           "causes": dict(s.get("causes") or {}),
                           "churn": dict(s.get("churn") or {})}
    out["compilation"] = {
        "programs": progs,
        "storms": sum_compile.get("storms") or [],
        "cache": sum_compile.get("cache") or None} \
        if (progs or sum_compile) else None
    utils = tel.get("utilization") or []
    sum_util = summary.get("utilization") or {}
    if utils or sum_util:
        mfus = [u["mfu"] for u in utils if u.get("mfu") is not None]
        bwus = [u["bw_util"] for u in utils
                if u.get("bw_util") is not None]
        out["utilization"] = {
            "device_kind": sum_util.get("device_kind"),
            "n_devices": sum_util.get("n_devices"),
            "mfu_p50": percentile(mfus, 50) if mfus
            else (sum_util.get("mfu") or {}).get("p50"),
            "mfu_p90": percentile(mfus, 90) if mfus
            else (sum_util.get("mfu") or {}).get("p90"),
            "bw_p50": percentile(bwus, 50) if bwus else None}
    else:
        out["utilization"] = None
    out["checkpoints"] = tel.get("checkpoints") or \
        (summary.get("checkpoint") or None)
    servings = tel.get("serving") or []
    out["serving"] = servings[-1] if servings \
        else (summary.get("serving") or None)
    out["decode"] = _last_by_name(tel.get("decode"),
                                  summary.get("decode"))
    out["router"] = _last_by_name(tel.get("router"),
                                  summary.get("router"))
    out["prefix_cache"] = _last_by_name(tel.get("prefix_cache"),
                                        summary.get("prefix_cache"))
    out["bucketing"] = _last_by_name(tel.get("bucketing"),
                                     summary.get("bucketing"))
    usage = _usage_view(tel, summary)
    if usage:
        rt = out["router"] or {}
        router_rec = next(iter(rt.values())) if len(rt) == 1 else None
        for u in usage.values():
            checks = _usage_checks(u, router_rec)
            u["reconcile_checks"] = [
                {"check": c[0], "meter": c[1], "counter": c[2],
                 "ok": c[3]} for c in checks]
            u["reconciled"] = all(c[3] for c in checks) \
                if checks else None
    out["usage"] = usage
    out["loss_scale"] = tel.get("loss_scale") or None
    out["alerts"] = tel.get("alerts") or summary.get("alerts") or []
    skipped = sum(s.get("skipped", 0) for s in steps)
    out["goodput"] = {
        "steps": len(steps),
        "productive": len(steps) - skipped,
        "skipped": skipped,
        "retried_ops": sum(s.get("retries", 0) for s in steps),
        "events": summary.get("events") or {},
        "fault": summary.get("fault") or {}}
    watermarks = {}
    for m in tel.get("memory") or []:
        dev = m.get("device", "?")
        peak = max(int(m.get("peak_bytes_in_use", 0) or 0),
                   int(m.get("bytes_in_use", 0) or 0))
        watermarks[dev] = max(watermarks.get(dev, 0), peak)
    if not watermarks and summary.get("memory"):
        watermarks = {d: w.get("peak_bytes_in_use", 0)
                      for d, w in summary["memory"].items()}
    out["memory"] = {
        "peak_bytes": watermarks or None,
        "breakdown": summary.get("memory_breakdown")
        or tel.get("breakdown")}
    out["comms"] = summary.get("comms") or None
    return out


# ---------------------------------------------------------------------------
# fleet mode: a directory or glob of per-rank / per-worker sinks
# ---------------------------------------------------------------------------

# the launcher's per-worker naming convention: rank 0 keeps the
# configured filename, rank N>0 gets base.workerN.ext (tools/launch.py,
# telemetry's per-worker sinks, MXNET_TRACE_FILE fan-out)
_WORKER_RE = re.compile(r"\.worker(\d+)\.[^.]+$")


def _sink_rank(name):
    m = _WORKER_RE.search(name)
    return int(m.group(1)) if m else 0


def read_fleet(paths):
    """Parse every input in ``paths``: telemetry JSONL sinks plus
    ``flightrec-*.json`` bundles. An unreadable or torn input becomes a
    counted entry in ``warnings`` and is skipped — the fleet report
    renders the survivors, it never aborts on one bad rank."""
    fleet = {"ranks": [], "bundles": [], "warnings": []}
    for path in paths:
        base = os.path.basename(path)
        if base.startswith("flightrec-") and base.endswith(".json"):
            try:
                with open(path) as f:
                    fleet["bundles"].append({"path": path,
                                             "bundle": json.load(f)})
            except (OSError, ValueError) as exc:
                fleet["warnings"].append(
                    "torn flight-recorder bundle %s skipped (%s)"
                    % (base, exc))
            continue
        try:
            tel = read_telemetry(path)
        except OSError as exc:
            fleet["warnings"].append(
                "unreadable sink %s skipped (%s)" % (base, exc))
            continue
        fleet["ranks"].append({"path": path, "rank": _sink_rank(base),
                               "tel": tel})
        if tel.get("skipped_lines"):
            fleet["warnings"].append(
                "%s: skipped %d unparseable line(s) — a killed rank "
                "strands at most one truncated trailing record"
                % (base, tel["skipped_lines"]))
    fleet["ranks"].sort(key=lambda r: (r["rank"], r["path"]))
    fleet["bundles"].sort(key=lambda b: b["path"])
    return fleet


def _rank_row(entry):
    """One cross-rank skew table row: the per-rank aggregates."""
    from ..telemetry import percentile
    tel = entry["tel"]
    steps = tel.get("steps") or []
    summary = tel.get("summary") or {}
    durs = [s["dur_ms"] for s in steps if s.get("dur_ms") is not None]
    totals = dict(summary.get("phases_ms") or {})
    if not totals:
        for s in steps:
            for phase, ms in (s.get("phases_ms") or {}).items():
                totals[phase] = totals.get(phase, 0.0) + ms
    n = len(durs)
    return {"rank": entry["rank"],
            "file": os.path.basename(entry["path"]),
            "run_id": (tel.get("run") or {}).get("run_id")
            or summary.get("run_id"),
            "gen": (summary.get("events") or {}).get(
                "supervisor_restart_generation", 0),
            "steps": n,
            "mean_ms": (sum(durs) / n) if n else None,
            "p50_ms": percentile(durs, 50) if n else None,
            "max_ms": max(durs) if n else None,
            "phase_mean_ms": {k: v / n for k, v in totals.items()}
            if n else {},
            "skipped_lines": tel.get("skipped_lines", 0)}


def _fleet_skew(rows):
    """Annotate each row with its delta vs the fastest rank and name
    the slowest rank, attributing its excess to the phase whose
    per-step mean exceeds the fleet mean the most."""
    timed = [r for r in rows if r["mean_ms"] is not None]
    if not timed:
        return None
    best = min(r["mean_ms"] for r in timed)
    for r in rows:
        r["delta_ms"] = (r["mean_ms"] - best) \
            if r["mean_ms"] is not None else None
    slow = max(timed, key=lambda r: r["mean_ms"])
    fleet_phase = {}
    for r in timed:
        for k, v in r["phase_mean_ms"].items():
            fleet_phase.setdefault(k, []).append(v)
    attribution = None
    if slow["phase_mean_ms"] and len(timed) > 1 and fleet_phase:
        deltas = {k: slow["phase_mean_ms"].get(k, 0.0)
                  - sum(vs) / len(vs)
                  for k, vs in fleet_phase.items()}
        phase = max(deltas, key=deltas.get)
        attribution = {"phase": phase, "delta_ms": deltas[phase]}
    return {"best_mean_ms": best, "slowest_rank": slow["rank"],
            "slowest_delta_ms": slow["mean_ms"] - best,
            "attribution": attribution}


def _fleet_serving(ranks):
    """Join router records against replica (decode) records across
    every sink: the conservation law is ``dispatched == admitted +
    replica-shed`` — every router dispatch lands in exactly one
    replica's submit accounting."""
    routers, servers = {}, {}
    alerts_lost = 0
    for e in ranks:
        tel = e["tel"]
        summary = tel.get("summary") or {}
        for name, rec in (_last_by_name(tel.get("router"),
                                        summary.get("router"))
                          or {}).items():
            routers[(e["rank"], name)] = rec
        for name, rec in (_last_by_name(tel.get("decode"),
                                        summary.get("decode"))
                          or {}).items():
            servers[(e["rank"], name)] = rec
        for a in tel.get("alerts") or (summary.get("alerts") or []):
            if a.get("kind") == "replica_lost":
                alerts_lost += 1
    if not routers and not servers:
        return None
    dispatched = sum(r.get("dispatched", 0) for r in routers.values())
    admitted = sum(s.get("requests", 0) - s.get("shed", 0)
                   for s in servers.values())
    replica_shed = sum(s.get("shed", 0) for s in servers.values())
    resume = [r.get("failover_resume_ms") for r in routers.values()
              if r.get("failover_resume_ms")]
    return {"routers": len(routers), "replicas": len(servers),
            "sessions": sum(r.get("requests", 0)
                            for r in routers.values()),
            "completed": sum(r.get("completed", 0)
                             for r in routers.values()),
            "dispatched": dispatched,
            "router_shed": sum(r.get("shed", 0)
                               for r in routers.values()),
            "admitted": admitted, "replica_shed": replica_shed,
            "reconciled": dispatched == admitted + replica_shed,
            "replicas_lost": sum(r.get("replicas_lost", 0)
                                 for r in routers.values()),
            "failovers": sum(r.get("failovers", 0)
                             for r in routers.values()),
            "replay_tokens": sum(r.get("replay_tokens", 0)
                                 for r in routers.values()),
            "resume_ms": resume,
            "replica_lost_alerts": alerts_lost}


def _bundle_summary(path, b):
    alert = b.get("alert") or {}
    ident = b.get("identity") or {}
    tr = b.get("trace") or {}
    return {"file": os.path.basename(path),
            "reason": b.get("reason"), "time": b.get("time"),
            "alert_kind": alert.get("kind"),
            "rank": ident.get("rank"), "gen": ident.get("gen"),
            "records": len(b.get("records") or ()),
            "trace_events": len(tr.get("traceEvents") or ()),
            "run_id": (b.get("run") or {}).get("run_id")}


def format_bundle_line(path, b):
    """The one-line flight-recorder bundle renderer."""
    s = _bundle_summary(path, b)
    return ("%-46s %-16s %-14s rank %s gen %s  %4d rec  %6d ev"
            % (s["file"][:46], (s["reason"] or "?")[:16],
               (s["alert_kind"] or "-")[:14], s["rank"], s["gen"],
               s["records"], s["trace_events"]))


def format_bundle(path, b):
    """The single-bundle detail view (diagnose on one
    ``flightrec-*.json``)."""
    lines = ["----------Flight-recorder bundle----------",
             format_bundle_line(path, b),
             "written      : %s" % (b.get("time") or "?")]
    alert = b.get("alert")
    if alert:
        lines.append("alert        : %s"
                     % json.dumps(alert, sort_keys=True))
    run = b.get("run")
    if run:
        lines.append("run          : %s"
                     % json.dumps(run, sort_keys=True))
    topo = b.get("topology")
    if topo:
        lines.append("topology     : %s"
                     % json.dumps(topo, sort_keys=True))
    ts = b.get("trace_stats")
    if ts:
        lines.append("trace        : %s"
                     % json.dumps(ts, sort_keys=True))
    return "\n".join(lines)


def _ms(v, sign=False):
    if v is None:
        return "-"
    return ("%+.3f" if sign else "%.3f") % v


def format_fleet(fleet):
    """Render the fleet report: cross-rank skew, restart-generation
    timeline, the router-vs-replica serving rollup, and one line per
    flight-recorder bundle."""
    rows = [_rank_row(e) for e in fleet["ranks"]]
    skew = _fleet_skew(rows)
    lines = ["----------Fleet telemetry----------",
             "sinks        : %d telemetry sink(s), %d flight-recorder "
             "bundle(s)" % (len(rows), len(fleet["bundles"]))]
    for w in fleet["warnings"]:
        lines.append("WARNING      : %s" % w)

    lines.append("----------Cross-rank skew----------")
    lines.append("%4s %4s %7s %10s %10s %10s %10s %10s  %s"
                 % ("rank", "gen", "steps", "mean(ms)", "p50(ms)",
                    "max(ms)", "wait(ms)", "vs best", "sink"))
    for r in rows:
        lines.append("%4s %4s %7d %10s %10s %10s %10s %10s  %s"
                     % (r["rank"], r["gen"], r["steps"],
                        _ms(r["mean_ms"]), _ms(r["p50_ms"]),
                        _ms(r["max_ms"]),
                        _ms(r["phase_mean_ms"].get("data_wait")),
                        _ms(r.get("delta_ms"), sign=True),
                        r["file"]))
    if skew:
        att = skew.get("attribution")
        lines.append("slowest      : rank %s (+%.3f ms/step vs best)%s"
                     % (skew["slowest_rank"],
                        skew["slowest_delta_ms"],
                        " — dominated by the '%s' phase (%+.3f ms "
                        "vs fleet mean)"
                        % (att["phase"], att["delta_ms"])
                        if att else ""))
    gens = sorted({r["gen"] for r in rows})
    if rows:
        if len(gens) == 1:
            lines.append("generations  : all ranks at restart "
                         "generation %s" % gens[0])
        else:
            lines.append("generations  : MIXED — ranks restarted "
                         "unevenly (a lagging rank resumed from an "
                         "older incarnation):")
            for r in rows:
                lines.append("  rank %-4s : generation %s (%s)"
                             % (r["rank"], r["gen"], r["file"]))

    sv = _fleet_serving(fleet["ranks"])
    bundles = [_bundle_summary(b["path"], b["bundle"])
               for b in fleet["bundles"]]
    if sv:
        lines.append("----------Fleet serving----------")
        lines.append("sessions     : %d submitted across %d router(s) "
                     "(completed %d, front-door shed %d)"
                     % (sv["sessions"], sv["routers"],
                        sv["completed"], sv["router_shed"]))
        lines.append("reconcile    : dispatched %d %s admitted %d + "
                     "replica-shed %d  [%s]"
                     % (sv["dispatched"],
                        "==" if sv["reconciled"] else "!=",
                        sv["admitted"], sv["replica_shed"],
                        "OK" if sv["reconciled"] else "MISMATCH"))
        lines.append("failover     : %d replica(s) lost, %d session(s) "
                     "re-homed, %d token(s) replayed by re-prefill"
                     % (sv["replicas_lost"], sv["failovers"],
                        sv["replay_tokens"]))
        for res in sv["resume_ms"]:
            lines.append("resume       : p50 %.3f ms  p99 %.3f ms  max "
                         "%.3f ms (loss detection -> first resumed "
                         "token)"
                         % (res.get("p50", 0.0), res.get("p99", 0.0),
                            res.get("max", 0.0)))
        n_alert_bundles = sum(1 for s in bundles
                              if s["alert_kind"] == "replica_lost")
        if sv["replica_lost_alerts"] or n_alert_bundles:
            ok = n_alert_bundles <= sv["replica_lost_alerts"]
            lines.append("flight rec   : %d replica_lost bundle(s) vs "
                         "%d replica_lost alert(s) across sinks  [%s]"
                         % (n_alert_bundles,
                            sv["replica_lost_alerts"],
                            "OK" if ok else "MISMATCH"))

    if fleet["bundles"]:
        lines.append("----------Flight recorder----------")
        for b in fleet["bundles"]:
            lines.append(format_bundle_line(b["path"], b["bundle"]))
    return "\n".join(lines)


def fleet_json(fleet):
    """The ``--format json`` mirror of :func:`format_fleet`."""
    rows = [_rank_row(e) for e in fleet["ranks"]]
    return {"sinks": len(rows),
            "warnings": list(fleet["warnings"]),
            "ranks": rows,
            "skew": _fleet_skew(rows),
            "serving": _fleet_serving(fleet["ranks"]),
            "bundles": [_bundle_summary(b["path"], b["bundle"])
                        for b in fleet["bundles"]]}


def _is_bundle_path(path):
    base = os.path.basename(path)
    return base.startswith("flightrec-") and base.endswith(".json")


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Diagnose the current system, or render a "
                    "telemetry JSONL run.")
    p.add_argument("telemetry", nargs="?", default=None,
                   help="path to a mxnet_tpu.telemetry JSONL sink, a "
                        "flightrec-*.json bundle, or a directory/glob "
                        "of per-rank sinks (fleet mode); when given, "
                        "render the tables and exit")
    p.add_argument("--format", choices=("text", "json"),
                   default="text", dest="format_",
                   help="text tables (default) or the same tables "
                        "mirrored as structured JSON records")
    for choice in ("python", "os", "hardware", "mxnet", "backend"):
        p.add_argument("--" + choice, default=1, type=int)
    p.add_argument("--timeout", default=30, type=int)
    args = p.parse_args(argv)
    if args.telemetry:
        target = args.telemetry
        paths = None
        if os.path.isdir(target):
            paths = sorted(
                _glob.glob(os.path.join(target, "*.jsonl"))
                + _glob.glob(os.path.join(target, "flightrec-*.json"))
                + _glob.glob(os.path.join(target, "*",
                                          "flightrec-*.json")))
            if not paths:
                p.error("no telemetry sinks or flightrec bundles "
                        "under directory %r" % target)
        elif not os.path.isfile(target) and \
                any(ch in target for ch in "*?["):
            paths = sorted(_glob.glob(target))
            if not paths:
                p.error("glob %r matched nothing" % target)
        elif not os.path.isfile(target):
            p.error("telemetry sink %r not found (expected a "
                    "mxnet_tpu.telemetry JSONL file)" % target)
        if paths is not None:
            fleet = read_fleet(paths)
            if args.format_ == "json":
                print(json.dumps(fleet_json(fleet), indent=2,
                                 sort_keys=True))
            else:
                print(format_fleet(fleet))
            return
        if _is_bundle_path(target):
            with open(target) as f:
                bundle = json.load(f)
            if args.format_ == "json":
                print(json.dumps(_bundle_summary(target, bundle),
                                 indent=2, sort_keys=True))
            else:
                print(format_bundle(target, bundle))
            return
        if args.format_ == "json":
            print(json.dumps(telemetry_json(read_telemetry(target)),
                             indent=2, sort_keys=True))
        else:
            print(format_telemetry(read_telemetry(target)))
        return
    if args.python:
        diagnose_python()
    if args.os:
        diagnose_os()
    if args.hardware:
        diagnose_hardware()
    if args.mxnet:
        diagnose_mxnet()
    if args.backend:
        diagnose_backend(args.timeout)


if __name__ == "__main__":
    main()
