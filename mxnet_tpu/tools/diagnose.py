"""Environment diagnosis (parity: tools/diagnose.py, minus the
network-reachability section — this environment has zero egress, so
the equivalent signal is backend reachability: a short-timeout
subprocess probe of the accelerator, the same probe bench.py and the
TPU test lane use).

Run: ``python -m mxnet_tpu.tools.diagnose``.
"""
from __future__ import annotations

import argparse
import os
import platform
import subprocess
import sys


def diagnose_python():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("Arch         :", platform.architecture())


def diagnose_os():
    print("----------System Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("node         :", platform.node())
    print("release      :", platform.release())
    print("version      :", platform.version())


def diagnose_hardware():
    print("----------Hardware Info----------")
    print("machine      :", platform.machine())
    print("processor    :", platform.processor())
    if sys.platform.startswith("linux"):
        try:
            out = subprocess.run(["lscpu"], capture_output=True,
                                 text=True, timeout=10)
            print(out.stdout.strip())
        except Exception:
            pass


def diagnose_mxnet():
    print("----------MXNet-TPU Info----------")
    import mxnet_tpu as mx
    from mxnet_tpu import runtime
    print("Version      :", getattr(mx, "__version__", "dev"))
    print("Directory    :", os.path.dirname(mx.__file__))
    feats = runtime.Features() if hasattr(runtime, "Features") else None
    if feats is not None:
        enabled = [str(f) for f in getattr(feats, "enabled", lambda: [])()] \
            if callable(getattr(feats, "enabled", None)) else []
        if enabled:
            print("Features     :", ", ".join(enabled))
    import jax
    import jaxlib
    print("jax          :", jax.__version__)
    print("jaxlib       :", jaxlib.__version__)
    knobs = {k: v for k, v in os.environ.items()
             if k.startswith(("MXNET_", "JAX_", "XLA_"))}
    for k in sorted(knobs):
        print("env %-24s: %s" % (k, knobs[k]))


def diagnose_backend(timeout):
    """Accelerator reachability (the zero-egress analogue of the
    reference's URL tests): jax.devices() in a subprocess so a hung
    backend cannot hang the diagnosis."""
    print("----------Backend Reachability----------")
    code = ("import jax; d = jax.devices(); "
            "print([(x.platform, x.device_kind) for x in d])")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout)
        if out.returncode == 0:
            print("devices      :", out.stdout.strip().splitlines()[-1])
        else:
            print("backend ERROR:", (out.stderr or "").strip()[-400:])
    except subprocess.TimeoutExpired:
        print("backend HUNG : jax.devices() did not answer within "
              "%ds — accelerator attachment is broken" % timeout)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Diagnose the current system.")
    for choice in ("python", "os", "hardware", "mxnet", "backend"):
        p.add_argument("--" + choice, default=1, type=int)
    p.add_argument("--timeout", default=30, type=int)
    args = p.parse_args(argv)
    if args.python:
        diagnose_python()
    if args.os:
        diagnose_os()
    if args.hardware:
        diagnose_hardware()
    if args.mxnet:
        diagnose_mxnet()
    if args.backend:
        diagnose_backend(args.timeout)


if __name__ == "__main__":
    main()
