"""Parse training logs into per-epoch tables (parity:
tools/parse_log.py): extracts ``Epoch[N] Train-<metric>=V``,
``Epoch[N] Validation-<metric>=V`` and ``Epoch[N] Time cost=V`` rows —
the format emitted by ``Module.fit``'s epoch logging and the reference
trainers.
"""
from __future__ import annotations

import argparse
import re


# value pattern: plain/negative decimals AND scientific notation —
# `([.\d]+)` silently truncated `1e-07` to `1` and dropped the sign of
# negative metrics (perplexity deltas)
_NUM = r"(-?[\d.]+(?:[eE][+-]?\d+)?)"


def parse(lines, metric_names=("accuracy",)):
    """Returns {epoch: {"train-<m>": v, "val-<m>": v, "time": v}}."""
    pats = []
    for m in metric_names:
        pats.append(("train-" + m, re.compile(
            r".*Epoch\[(\d+)\] Train-" + re.escape(m) + r".*=" + _NUM)))
        pats.append(("val-" + m, re.compile(
            r".*Epoch\[(\d+)\] Validation-" + re.escape(m)
            + r".*=" + _NUM)))
    pats.append(("time", re.compile(
        r".*Epoch\[(\d+)\] Time.*=" + _NUM)))
    table = {}
    for line in lines:
        for name, pat in pats:
            m = pat.match(line)
            if m:
                epoch = int(m.group(1))
                table.setdefault(epoch, {})[name] = float(m.group(2))
    return table


def format_table(table, metric_names=("accuracy",)):
    cols = ["time"]
    for m in metric_names:
        cols += ["train-" + m, "val-" + m]
    out = ["epoch\t" + "\t".join(cols)]
    for epoch in sorted(table):
        row = table[epoch]
        out.append("\t".join([str(epoch)] + [
            ("%.6g" % row[c]) if c in row else "-" for c in cols]))
    return "\n".join(out)


def main(argv=None):
    p = argparse.ArgumentParser(description="parse mxnet training logs")
    p.add_argument("logfile")
    p.add_argument("--metric-names", nargs="+", default=["accuracy"])
    args = p.parse_args(argv)
    with open(args.logfile) as f:
        table = parse(f, tuple(args.metric_names))
    print(format_table(table, tuple(args.metric_names)))
    return table


if __name__ == "__main__":
    main()
