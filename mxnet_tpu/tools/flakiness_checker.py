"""Flakiness checker (parity: tools/flakiness_checker.py): run one
test many times with different seeds to estimate flakiness.

Run: ``python -m mxnet_tpu.tools.flakiness_checker
tests/test_operator.py::test_optimizer_ops -n 20``.
"""
from __future__ import annotations

import argparse
import os
import random
import subprocess
import sys

DEFAULT_NUM_TRIALS = 10


def run_test_trials(test_path, num_trials, seed=None, verbose=False):
    """Run the test ``num_trials`` times under fresh MXNET_TEST_SEEDs;
    returns (failures, seeds_used)."""
    failures = []
    seeds = []
    base = random.Random(seed)
    for trial in range(num_trials):
        s = base.randint(0, 2 ** 31 - 1)
        seeds.append(s)
        # MXNET_TEST_SEED is WRITTEN for the child process here, not
        # read — the typed read side lives in tests/conftest.py
        env = dict(os.environ, MXNET_TEST_SEED=str(s))
        out = subprocess.run(
            [sys.executable, "-m", "pytest", test_path, "-x", "-q"],
            capture_output=True, text=True, env=env)
        status = "PASS" if out.returncode == 0 else "FAIL"
        if verbose or status == "FAIL":
            print("trial %d seed %d: %s" % (trial, s, status),
                  flush=True)
        if out.returncode != 0:
            failures.append((s, out.stdout[-2000:]))
    return failures, seeds


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Check a test for flakiness")
    p.add_argument("test", help="pytest node id, e.g. "
                                "tests/test_ndarray.py::test_basic")
    p.add_argument("-n", "--num-trials", type=int,
                   default=DEFAULT_NUM_TRIALS)
    p.add_argument("-s", "--seed", type=int, default=None)
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)
    failures, seeds = run_test_trials(args.test, args.num_trials,
                                      args.seed, args.verbose)
    print("%d/%d trials failed" % (len(failures), args.num_trials))
    for s, tail in failures:
        print("--- seed %d ---" % s)
        print(tail)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
