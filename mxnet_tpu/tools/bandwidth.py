"""KVStore push/pull bandwidth probe (parity:
tools/bandwidth/measure.py — the harness behind BASELINE.md metric #2
and docs/faq/perf.md:246).

Measures aggregate GB/s of repeated push+pull rounds over layer-sized
arrays (by default the weight shapes of a model-zoo network, like the
reference measuring a real network's gradient set), with an optional
correctness check of the reduced values.

Run: ``python -m mxnet_tpu.tools.bandwidth --network resnet18_v1
--num-batches 5``.
"""
from __future__ import annotations

import argparse
import logging
import time

import numpy as np


def _layer_shapes(network, num_classes, image_shape):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    net = getattr(vision, network)(classes=num_classes)
    net.initialize(mx.init.Xavier())
    c, h, w = image_shape
    net(mx.nd.zeros((1, c, h, w)))
    return [tuple(p.data().shape)
            for p in net.collect_params().values()]


def measure(shapes, kv_type="local", num_workers=2, num_batches=5,
            test_results=True, optimizer=None, gc_type="none"):
    """One result row per batch: dict with error count and GB/s.
    Accounting matches the reference: each push moves every worker's
    copy once and each pull moves the merged value back, so one round
    is ``2 * total_bytes`` per worker-copy."""
    import mxnet_tpu as mx
    kv = mx.kv.create(kv_type)
    if gc_type != "none":
        kv.set_gradient_compression({"type": gc_type})
    if optimizer:
        kv.set_optimizer(mx.optimizer.create(optimizer))
    for i, s in enumerate(shapes):
        kv.init(i, mx.nd.zeros(s))
    total_bytes = sum(int(np.prod(s)) * 4 for s in shapes)
    results = []
    for b in range(num_batches):
        t0 = time.time()
        errors = 0
        pending = []
        for i, s in enumerate(shapes):
            vals = [mx.nd.ones(s) * (w + 1)
                    for w in range(num_workers)]
            outs = [mx.nd.zeros(s) for _ in range(num_workers)]
            kv.push(i, vals)
            kv.pull(i, out=outs)
            pending.extend(outs)
            if test_results and optimizer is None:
                want = sum(w + 1 for w in range(num_workers))
                if not np.allclose(outs[0].asnumpy(), want):
                    errors += 1
        # wait on EVERY key's outputs before the end timestamp (waiting
        # only on the last shape lets earlier keys still be in flight,
        # overstating GB/s — and NameErrors on an empty shape list)
        for o in pending:
            o.wait_to_read()
        dt = time.time() - t0
        gbps = 2 * total_bytes * num_workers / dt / 1e9
        results.append({"batch": b, "error": errors,
                        "time_s": round(dt, 4),
                        "bandwidth_gbps": round(gbps, 6)})
    return results


def main(argv=None):
    p = argparse.ArgumentParser(
        description="benchmark kvstore push/pull bandwidth")
    p.add_argument("--network", type=str, default="resnet18_v1")
    p.add_argument("--num-workers", type=int, default=2,
                   help="simulated worker copies per key")
    p.add_argument("--kv-store", type=str, default="local")
    p.add_argument("--num-batches", type=int, default=5)
    p.add_argument("--test-results", type=int, default=1)
    p.add_argument("--image-shape", type=str, default="3,32,32")
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--optimizer", type=str, default="None")
    p.add_argument("--gc-type", type=str, default="none")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    shapes = _layer_shapes(args.network, args.num_classes,
                           tuple(int(x) for x in
                                 args.image_shape.split(",")))
    results = measure(
        shapes, kv_type=args.kv_store, num_workers=args.num_workers,
        num_batches=args.num_batches,
        test_results=bool(args.test_results),
        optimizer=None if args.optimizer == "None" else args.optimizer,
        gc_type=args.gc_type)
    for r in results:
        logging.info("iter %d: %.3f GB/s, %d errors, %.4f s",
                     r["batch"], r["bandwidth_gbps"], r["error"],
                     r["time_s"])
    return results


if __name__ == "__main__":
    main()
