"""Rebuild the .idx sidecar from a .rec file (parity:
tools/rec2idx.py): scan the RecordIO framing, record each record's
byte offset, and write tab-separated ``key\\toffset`` rows keyed by the
record's IRHeader id (or sequential position with --sequential-keys).
"""
from __future__ import annotations

import argparse

from .. import recordio


def build_index(rec_path, idx_path, sequential_keys=False):
    import os
    reader = recordio.MXRecordIO(rec_path, "r")
    n = 0
    # tmp + os.replace: a crash mid-index must not leave a
    # truncated .idx that silently drops records
    tmp = "%s.tmp.%d" % (idx_path, os.getpid())
    with open(tmp, "w") as fidx:
        while True:
            offset = reader.tell()
            payload = reader.read()
            if payload is None:
                break
            if sequential_keys:
                key = n
            else:
                header, _ = recordio.unpack(payload)
                key = int(header.id)
            fidx.write("%d\t%d\n" % (key, offset))
            n += 1
    os.replace(tmp, idx_path)
    reader.close()
    return n


def main(argv=None):
    p = argparse.ArgumentParser(
        description="create a RecordIO index file")
    p.add_argument("record", help="path to the .rec file")
    p.add_argument("index", help="path of the .idx file to write")
    p.add_argument("--sequential-keys", action="store_true",
                   help="key by position instead of header id")
    args = p.parse_args(argv)
    n = build_index(args.record, args.index, args.sequential_keys)
    print("wrote %d index entries to %s" % (n, args.index))
    return n


if __name__ == "__main__":
    main()
