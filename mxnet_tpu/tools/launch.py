"""Multi-process job launcher (parity: tools/launch.py:33).

``python -m mxnet_tpu.tools.launch -n 4 python train.py`` spawns N
worker processes on this host with the reference's DMLC_* environment
contract (DMLC_NUM_WORKER / DMLC_WORKER_ID / DMLC_PS_ROOT_URI /
DMLC_PS_ROOT_PORT). Workers need no launcher-specific code: creating a
``tpu_sync`` (dist) KVStore reads that contract and joins the process
group via ``jax.distributed.initialize`` — the coordinator replaces the
reference's ps-lite scheduler, and collectives replace the server pool,
so there is no -s/--num-servers role to launch (accepted and ignored
for CLI compatibility).

Only the ``local`` launcher is implemented: multi-host jobs on TPU
pods are started by the cluster scheduler (GKE/xmanager), which
provides its own coordinator wiring — ssh/mpi/sge/yarn trackers exist
to solve a problem the TPU runtime does not have. They raise with that
explanation.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys

__all__ = ["launch_local", "main"]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_local(num_workers, command, extra_env=(), port=None):
    """Spawn ``command`` num_workers times with the DMLC_* env contract;
    returns the list of exit codes."""
    port = port or _free_port()
    procs = []
    for i in range(num_workers):
        env = dict(os.environ)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": str(num_workers),
            "DMLC_WORKER_ID": str(i),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
        })
        for kv in extra_env:
            k, _, v = kv.partition(":")
            env[k] = v
        procs.append(subprocess.Popen(command, env=env))
    codes = []
    try:
        for p in procs:
            codes.append(p.wait())
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        raise
    return codes


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job (local "
                    "multi-process; ref tools/launch.py)")
    parser.add_argument("-n", "--num-workers", required=True, type=int)
    parser.add_argument("-s", "--num-servers", type=int, default=None,
                        help="accepted for CLI parity; the collective "
                             "backend has no server role")
    parser.add_argument("--launcher", default="local",
                        choices=["local", "ssh", "mpi", "sge", "yarn"])
    parser.add_argument("-H", "--hostfile", default=None)
    parser.add_argument("--env", action="append", default=[],
                        help="KEY:VALUE set in every worker")
    parser.add_argument("--sync-dst-dir", default=None)
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    if args.launcher != "local":
        raise NotImplementedError(
            "launcher %r: multi-host TPU jobs are started by the "
            "cluster scheduler (see module docstring); use --launcher "
            "local for single-host multi-process" % args.launcher)
    codes = launch_local(args.num_workers, args.command,
                         extra_env=args.env)
    bad = [(i, c) for i, c in enumerate(codes) if c != 0]
    for i, c in bad:
        print("worker %d exited with %d" % (i, c), file=sys.stderr)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
