"""Multi-process job launcher (parity: tools/launch.py:33).

``python -m mxnet_tpu.tools.launch -n 4 python train.py`` spawns N
worker processes on this host with the reference's DMLC_* environment
contract (DMLC_NUM_WORKER / DMLC_WORKER_ID / DMLC_PS_ROOT_URI /
DMLC_PS_ROOT_PORT). Workers need no launcher-specific code: creating a
``tpu_sync`` (dist) KVStore — or calling ``parallel.distributed.init``
— reads that contract and joins the process group via
``jax.distributed.initialize``; the coordinator replaces the
reference's ps-lite scheduler and collectives replace the server pool,
so there is no -s/--num-servers role to launch (accepted and ignored
for CLI compatibility).

**Failure semantics (non-supervised):** the first worker to exit
nonzero triggers a teardown of the survivors — SIGTERM, a
``MXNET_LAUNCH_GRACE`` window, then SIGKILL — and the launcher exits
with THAT worker's code (no orphans, no masked exit status).

**Supervised mode (``--supervise``):** the launcher becomes the
restart-the-world supervisor real TPU pods use. It arms the heartbeat
contract (``MXNET_HB_DIR`` — every worker runs a writer + peer
monitor, ``parallel.multihost``), watches both process exits and
heartbeat staleness (a wedged-but-alive world is torn down too), and
on a failure kills the surviving workers, scans ``--resume-prefix``
for the newest VALID manifest epoch, and relaunches the whole job with
``MXNET_LAUNCH_RESTART`` (generation) and ``MXNET_LAUNCH_RESUME_EPOCH``
set so workers resume instead of starting over. Backoff doubles from
``MXNET_LAUNCH_BACKOFF`` per consecutive restart, the budget is
``MXNET_LAUNCH_MAX_RESTARTS``, and ``MXNET_LAUNCH_ALLOW_SHRINK=1``
permits a degraded relaunch at N-1 workers when a replacement is not
expected (the elastic manifest format makes the resumed topology a
free choice). ``--events-file`` appends one JSON line per supervisor
event (worker death, teardown, restart, give-up) — the
detection-to-restart timing source for ``bench.py --multihost``.

Only the ``local`` launcher is implemented: multi-host jobs on TPU
pods are started by the cluster scheduler (GKE/xmanager), which
provides its own coordinator wiring — ssh/mpi/sge/yarn trackers exist
to solve a problem the TPU runtime does not have. They raise with that
explanation.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

__all__ = ["launch_local", "supervise", "worker_contract", "main"]


def worker_contract():
    """This process's launcher worker contract, or ``None`` outside a
    launched worker set: ``{"rank", "world", "uri", "port"}`` read
    from the DMLC_* environment ``_spawn_workers`` sets. Serving
    workers use it to name their router replica ``replica-<rank>`` so
    the router, /metrics labels, and the supervisor's event log all
    speak the same id."""
    if os.environ.get("DMLC_ROLE") != "worker":
        return None
    try:
        return {"rank": int(os.environ["DMLC_WORKER_ID"]),
                "world": int(os.environ["DMLC_NUM_WORKER"]),
                "uri": os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
                "port": int(os.environ.get("DMLC_PS_ROOT_PORT", 0))}
    except (KeyError, ValueError):
        return None


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _grace_seconds():
    from .. import envs
    return max(float(envs.get_float("MXNET_LAUNCH_GRACE")), 0.0)


def _spawn_workers(num_workers, command, extra_env=(), port=None,
                   extra=None):
    """Spawn the DMLC_* worker set; returns (procs, port)."""
    port = port or _free_port()
    procs = []
    for i in range(num_workers):
        env = dict(os.environ)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": str(num_workers),
            "DMLC_WORKER_ID": str(i),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
        })
        # a shared trace sink would be clobbered N ways at exit; give
        # each rank its own export (base.workerN.json) so
        # tracing.merge_exports can clock-align the set afterwards —
        # the same per-worker split telemetry sinks already get
        trace_file = env.get("MXNET_TRACE_FILE", "")
        if trace_file and num_workers > 1 and i != 0:
            # rank 0 keeps the configured name — the same convention
            # telemetry's per-worker JSONL sinks use
            base, ext = os.path.splitext(trace_file)
            env["MXNET_TRACE_FILE"] = "%s.worker%d%s" % (base, i, ext)
        if extra:
            env.update(extra)
        for kv in extra_env:
            k, _, v = kv.partition(":")
            env[k] = v
        procs.append(subprocess.Popen(command, env=env))
    return procs, port


def _exit_code(code):
    """Normalize a Popen returncode into a shell exit code: signal
    deaths (negative) map to the conventional 128+signum; ``None``
    (the supervisor's synthetic hb-silence marker) maps to 1."""
    if code is None:
        return 1
    code = int(code)
    if code < 0:
        return 128 + (-code) if -code < 128 else 1
    return code


def _teardown(procs, grace=None):
    """SIGTERM every live worker, wait out the grace window, SIGKILL
    the stragglers — the no-orphans discipline both the failure path
    and the supervisor share."""
    live = [p for p in procs if p.poll() is None]
    for p in live:
        try:
            p.send_signal(signal.SIGTERM)
        except OSError:
            pass
    deadline = time.monotonic() + (_grace_seconds() if grace is None
                                   else grace)
    for p in live:
        while p.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        if p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass
            p.wait()


def _wait_first_failure(procs, poll_s=0.1, hb_dir=None,
                        hb_timeout_s=None):
    """Poll until every worker exited cleanly, or one failed.
    Returns ``(failed_rank, exit_code)`` — ``(None, 0)`` on full
    success. With ``hb_dir`` given, a WHOLE-WORLD heartbeat silence
    past ``hb_timeout_s`` also counts as a failure (rank -1): the
    in-job monitors usually exit a wedged world themselves, but a
    world wedged before the monitors armed (or with every monitor
    stuck) still needs the supervisor's outside view."""
    while True:
        running = False
        for rank, p in enumerate(procs):
            code = p.poll()
            if code is None:
                running = True
            elif code != 0:
                return rank, code
        if not running:
            return None, 0
        if hb_dir is not None and hb_timeout_s:
            freshest = None
            any_file = False
            for rank in range(len(procs)):
                try:
                    age = time.time() - os.stat(
                        os.path.join(hb_dir, "hb-%d" % rank)).st_mtime
                    any_file = True
                    freshest = age if freshest is None \
                        else min(freshest, age)
                except OSError:
                    continue
            if any_file and freshest is not None \
                    and freshest > hb_timeout_s:
                # synthetic marker: no worker exited, the WORLD went
                # silent — code None maps to exit 1, never aliasing a
                # real signal death
                return -1, None
        time.sleep(poll_s)


def launch_local(num_workers, command, extra_env=(), port=None,
                 extra=None):
    """Spawn ``command`` num_workers times with the DMLC_* env
    contract and wait. The FIRST nonzero exit tears the surviving
    workers down (SIGTERM → MXNET_LAUNCH_GRACE → SIGKILL) and its
    code is returned as the job's; a fully clean run returns 0."""
    procs, _ = _spawn_workers(num_workers, command,
                              extra_env=extra_env, port=port,
                              extra=extra)
    try:
        rank, code = _wait_first_failure(procs)
    except KeyboardInterrupt:
        _teardown(procs)
        raise
    if rank is not None:
        print("launch: worker %d exited with %d — tearing down the "
              "remaining workers" % (rank, code), file=sys.stderr)
        _teardown(procs)
        return _exit_code(code)
    return 0


class _Events:
    """Append-only JSONL event log for the supervisor (bench + tests
    read detection/restart timings from it)."""

    def __init__(self, path):
        self.path = path
        self.t0 = time.monotonic()

    def emit(self, kind, **fields):
        rec = {"t": round(time.monotonic() - self.t0, 4),
               "kind": kind}
        rec.update(fields)
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        print("launch-supervisor: %s %s"
              % (kind, json.dumps(fields)), file=sys.stderr)


def _scan_resume_epoch(prefix):
    """The newest valid manifest epoch under ``prefix`` (the restart's
    resume point), or None. Validation matches the training-side scan:
    torn epochs are skipped, not trusted."""
    if not prefix:
        return None
    from ..checkpoint import latest_manifest_epoch
    return latest_manifest_epoch(prefix)


def supervise(num_workers, command, extra_env=(), resume_prefix=None,
              events_file=None, max_restarts=None, hb_dir=None):
    """Run the job under restart-the-world supervision; returns the
    final exit code (0 = a launch attempt finished clean)."""
    from .. import envs
    if max_restarts is None:
        max_restarts = envs.get_int("MXNET_LAUNCH_MAX_RESTARTS")
    backoff = max(float(envs.get_float("MXNET_LAUNCH_BACKOFF")), 0.0)
    allow_shrink = bool(envs.get_bool("MXNET_LAUNCH_ALLOW_SHRINK"))
    hb_timeout_s = max(envs.get_int("MXNET_HB_TIMEOUT_MS"), 1) / 1e3
    owns_hb = hb_dir is None and not envs.get_path("MXNET_HB_DIR")
    if owns_hb:
        hb_dir = tempfile.mkdtemp(prefix="mxhb-")
    elif hb_dir is None:
        hb_dir = envs.get_path("MXNET_HB_DIR")
    events = _Events(events_file)
    n = int(num_workers)
    restarts = 0
    code = 0
    while True:
        resume_epoch = _scan_resume_epoch(resume_prefix)
        extra = {"MXNET_HB_DIR": hb_dir,
                 "MXNET_LAUNCH_RESTART": str(restarts)}
        if resume_epoch is not None:
            extra["MXNET_LAUNCH_RESUME_EPOCH"] = str(resume_epoch)
        else:
            extra["MXNET_LAUNCH_RESUME_EPOCH"] = ""
        # a fresh attempt starts with a clean heartbeat slate: stale
        # beat files and departure markers from the previous
        # generation must not confuse the new world's monitors
        try:
            for f in os.listdir(hb_dir):
                if f.startswith("hb-"):
                    os.unlink(os.path.join(hb_dir, f))
        except OSError:
            pass
        events.emit("launch", attempt=restarts, workers=n,
                    resume_epoch=resume_epoch)
        t_launch = time.monotonic()
        procs, _ = _spawn_workers(n, command, extra_env=extra_env,
                                  extra=extra)
        try:
            rank, code = _wait_first_failure(
                procs, hb_dir=hb_dir, hb_timeout_s=10 * hb_timeout_s)
        except KeyboardInterrupt:
            _teardown(procs)
            raise
        if rank is None:
            events.emit("success", attempt=restarts,
                        wall_s=round(time.monotonic() - t_launch, 3))
            return 0
        t_detect = time.monotonic()
        events.emit("worker_failed", attempt=restarts, rank=rank,
                    code=code,
                    detect_s=round(t_detect - t_launch, 3))
        _teardown(procs)
        events.emit("teardown", attempt=restarts,
                    teardown_s=round(time.monotonic() - t_detect, 3))
        if restarts >= max_restarts:
            events.emit("give_up", attempt=restarts, code=code)
            return _exit_code(code) or 1
        delay = backoff * (2.0 ** restarts)
        restarts += 1
        if allow_shrink and n > 1:
            # degraded relaunch: no replacement host is coming; the
            # elastic manifests make the smaller topology a resume,
            # not a retrain
            n -= 1
        events.emit("restart", attempt=restarts, workers=n,
                    backoff_s=round(delay, 3))
        time.sleep(delay)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job (local "
                    "multi-process; ref tools/launch.py)")
    parser.add_argument("-n", "--num-workers", required=True, type=int)
    parser.add_argument("-s", "--num-servers", type=int, default=None,
                        help="accepted for CLI parity; the collective "
                             "backend has no server role")
    parser.add_argument("--launcher", default="local",
                        choices=["local", "ssh", "mpi", "sge", "yarn"])
    parser.add_argument("-H", "--hostfile", default=None)
    parser.add_argument("--env", action="append", default=[],
                        help="KEY:VALUE set in every worker")
    parser.add_argument("--sync-dst-dir", default=None)
    parser.add_argument("--supervise", action="store_true",
                        help="restart-the-world supervision: detect a "
                             "dead/wedged worker, tear the job down, "
                             "relaunch resuming from the last good "
                             "manifest epoch")
    parser.add_argument("--resume-prefix", default=None,
                        help="checkpoint prefix the supervisor scans "
                             "for the newest valid manifest epoch on "
                             "each restart")
    parser.add_argument("--events-file", default=None,
                        help="append supervisor events as JSON lines "
                             "(detection/teardown/restart timings)")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    if args.launcher != "local":
        raise NotImplementedError(
            "launcher %r: multi-host TPU jobs are started by the "
            "cluster scheduler (see module docstring); use --launcher "
            "local for single-host multi-process" % args.launcher)
    if args.supervise:
        return supervise(args.num_workers, args.command,
                         extra_env=args.env,
                         resume_prefix=args.resume_prefix,
                         events_file=args.events_file)
    return launch_local(args.num_workers, args.command,
                        extra_env=args.env)


if __name__ == "__main__":
    sys.exit(main())
