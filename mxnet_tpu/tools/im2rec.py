"""im2rec — pack an image dataset into RecordIO (reference:
tools/im2rec.py / tools/im2rec.cc).

Two stages, same as the reference tool:
- :func:`make_list` walks an image directory tree and writes the
  ``.lst`` file (``index\tlabel\trelative_path`` rows, labels assigned
  per subdirectory).
- :func:`im2rec` reads a ``.lst``, JPEG-encodes each image (optionally
  resizing the shorter edge), and writes the ``.rec`` + ``.idx`` pair
  via :class:`MXIndexedRecordIO` with IRHeader packing.

Usable as a CLI: ``python -m mxnet_tpu.tools.im2rec prefix root``.
"""
from __future__ import annotations

import argparse
import logging
import os

import numpy as np

from ..recordio import MXIndexedRecordIO, IRHeader, pack, pack_img

__all__ = ["make_list", "im2rec", "read_list"]

_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(root, prefix, recursive=True, shuffle=False, seed=0):
    """Write ``prefix.lst`` over the images under ``root``; one class
    label per immediate subdirectory (reference: im2rec.py list_image)."""
    entries = []
    classes = {}
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        rel_dir = os.path.relpath(dirpath, root)
        for fname in sorted(filenames):
            if not fname.lower().endswith(_EXTS):
                continue
            label = classes.setdefault(
                rel_dir if rel_dir != "." else "", len(classes))
            entries.append((label,
                            os.path.normpath(os.path.join(rel_dir,
                                                          fname))))
        if not recursive:
            break
    if shuffle:
        np.random.RandomState(seed).shuffle(entries)
    lst_path = prefix + ".lst"
    tmp = "%s.tmp.%d" % (lst_path, os.getpid())
    with open(tmp, "w") as out:
        for i, (label, rel) in enumerate(entries):
            out.write("%d\t%f\t%s\n" % (i, float(label), rel))
    os.replace(tmp, lst_path)
    return lst_path, classes


def read_list(lst_path):
    """Yield (index, label(s), relative_path) rows of a .lst file."""
    with open(lst_path) as f:
        for line in f:
            cells = line.strip().split("\t")
            if len(cells) < 3:
                continue
            idx = int(cells[0])
            labels = [float(x) for x in cells[1:-1]]
            yield idx, labels, cells[-1]


def im2rec(lst_path, root, prefix, quality=95, resize=0,
           encoding=".jpg", pass_through=False):
    """Pack every .lst row into ``prefix.rec`` + ``prefix.idx``
    (reference: im2rec.py write_record)."""
    rec = MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, labels, rel in read_list(lst_path):
        path = os.path.join(root, rel)
        label = labels[0] if len(labels) == 1 else np.asarray(labels)
        header = IRHeader(0, label, idx, 0)
        if pass_through:
            with open(path, "rb") as f:
                payload = pack(header, f.read())
        else:
            img = _load_image(path, resize)
            payload = pack_img(header, img, quality=quality,
                               img_fmt=encoding)
        rec.write_idx(idx, payload)
        count += 1
    rec.close()
    logging.info("im2rec: wrote %d records to %s.rec", count, prefix)
    return count


def _load_image(path, resize):
    try:
        import cv2
        img = cv2.imread(path, cv2.IMREAD_COLOR)
        if img is None:
            raise IOError("cv2 failed to read %s" % path)
        if resize:
            h, w = img.shape[:2]
            if h < w:
                nh, nw = resize, int(round(w * resize / h))
            else:
                nh, nw = int(round(h * resize / w)), resize
            img = cv2.resize(img, (nw, nh))
        return img
    except ImportError:
        from PIL import Image
        img = Image.open(path).convert("RGB")
        if resize:
            w, h = img.size
            if h < w:
                nh, nw = resize, int(round(w * resize / h))
            else:
                nh, nw = int(round(h * resize / w)), resize
            img = img.resize((nw, nh))
        # cv2 absent ⇒ pack_img will also encode via PIL, which
        # expects RGB — keep PIL's native channel order
        return np.asarray(img)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix", help="output prefix for .lst/.rec/.idx")
    ap.add_argument("root", help="image directory root")
    ap.add_argument("--no-list", action="store_true",
                    help="reuse an existing prefix.lst")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--resize", type=int, default=0)
    ap.add_argument("--shuffle", action="store_true")
    args = ap.parse_args()
    if not args.no_list:
        make_list(args.root, args.prefix, shuffle=args.shuffle)
    im2rec(args.prefix + ".lst", args.root, args.prefix,
           quality=args.quality, resize=args.resize)


if __name__ == "__main__":
    main()
