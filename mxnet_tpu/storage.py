"""Device-memory introspection (the TPU stand-in for the reference's
pooled Storage managers, src/storage/ — SURVEY §7: HBM pooling is
XLA's job, so this module exposes the *stats* surface instead)."""
from __future__ import annotations

__all__ = ["memory_stats", "bytes_allocated", "bytes_limit",
           "pool_snapshot"]


def _device(dev=None):
    import jax
    return jax.devices()[dev] if isinstance(dev, int) else \
        (dev if dev is not None else jax.devices()[0])


def memory_stats(device=None):
    """Raw allocator statistics for one device (bytes_in_use,
    peak_bytes_in_use, bytes_limit, num_allocs, ...) as reported by the
    runtime; {} when the backend exposes none (CPU)."""
    d = _device(device)
    stats = getattr(d, "memory_stats", None)
    try:
        return dict(stats() or {}) if callable(stats) else {}
    except Exception:
        return {}


def bytes_allocated(device=None):
    return int(memory_stats(device).get("bytes_in_use", 0))


def bytes_limit(device=None):
    return int(memory_stats(device).get("bytes_limit", 0))


def pool_snapshot():
    """Per-device {device: stats} across all visible devices — the
    analogue of dumping every pooled storage manager's counters."""
    import jax
    return {str(d): memory_stats(d) for d in jax.devices()}
