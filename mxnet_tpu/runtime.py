"""Runtime feature detection (parity: python/mxnet/runtime.py +
src/libinfo.cc). Features reflect the TPU-native build."""
from __future__ import annotations

__all__ = ["Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return "%s %s" % ("✔" if self.enabled else "✖", self.name)


def _detect():
    import jax
    feats = {
        "TPU": any(d.platform != "cpu" for d in jax.devices()),
        "XLA": True,
        "PALLAS": True,
        "CUDA": False, "CUDNN": False, "NCCL": False, "TENSORRT": False,
        "MKLDNN": False,
        "OPENCV": _has("cv2"),
        "DIST_KVSTORE": True,
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": True,
        "F16C": True,
        "JAX_DISTRIBUTED": True,
    }
    return {k: Feature(k, v) for k, v in feats.items()}


def _has(mod):
    try:
        __import__(mod)
        return True
    except ImportError:
        return False


class Features(dict):
    def __init__(self):
        super().__init__(_detect())

    def is_enabled(self, name):
        return self[name.upper()].enabled


def feature_list():
    return list(Features().values())
