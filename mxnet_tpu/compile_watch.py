"""Compile & hardware-utilization observability (SURVEY §5.1 gap #2).

The telemetry layer answers *where did this step's wall-clock go*; this
module answers the two questions a TPU-native stack lives or dies by:

1. **How much did XLA compilation cost this run — and why did it
   recompile?** Every framework ``jax.jit`` site (the executor's
   forward / forward+backward programs, the fused train step, the
   per-op eager jit cache that backs ``CachedOp``, the eager
   collectives, and the inference server's bucket-ladder programs —
   ``serving:bN``, one per bucket, staged through :func:`jit` so the
   "fixed program cache under arbitrary request mixes" claim is a
   checkable :func:`site_stats` oracle) routes through :func:`jit`,
   which stages compilation explicitly (``lower()`` + ``compile()``)
   so each compile is:

   - timed (per-compile duration + cumulative compile seconds),
   - keyed (the argument-signature cache key that triggered it),
   - diffed against the previous key of the same *logical program*
     (same site name, across executor rebinds), naming the argument
     whose shape/dtype/weak-type/sharding changed — the
     **recompile cause**,
   - mined for XLA's own ``cost_analysis()`` (flops, bytes accessed)
     and ``memory_analysis()`` where the backend provides them —
     consulted ONCE per compile, never per step.

   A **recompile storm** — ``MXNET_COMPILE_STORM_K`` (default 3)
   compiles of one program within ``MXNET_COMPILE_STORM_STEPS``
   (default 50) steps — fires a one-time warning naming the churning
   argument, the classic symptom of an unpadded/unbucketed input loop.

2. **What fraction of the hardware's peak did each step achieve?**
   Every watched dispatch accrues its executable's flops/bytes into the
   current step; at each telemetry step boundary the accumulators
   combine with the step's wall time into **MFU** (model-flops
   utilization) and memory-bandwidth utilization against a per-device
   peak table (built-in numbers for TPU generations, a placeholder for
   CPU, both overridable via ``MXNET_DEVICE_PEAK_FLOPS`` /
   ``MXNET_DEVICE_PEAK_BW`` — per-device values in FLOP/s and bytes/s).
   The peak is **dtype-aware**: each compiled program's flops are
   normalized by its compute dtype's ``PEAK_DTYPE_FACTOR`` (narrowest
   float in the argument signature — fp32 at half the bf16 MXU rate,
   int8 at double), so AMP, fp32, and int8 programs all report MFU
   against the peak they could actually reach.

Everything flows into the active telemetry run: ``compile`` and
``utilization`` JSONL record kinds, plus ``compile``/``utilization``
blocks in the ``summary`` record; ``python -m mxnet_tpu.tools.diagnose
run.jsonl`` renders the compile log and the utilization table.
Compiles at the fused-step sites additionally bridge into
``profiler.counters()`` as ``fused_step_compile_ms`` so the fused
cache's hit/miss counters and its compile seconds reconcile in one
place.

Off by default, always cheap when off: a watched function's call path
is one module-global ``None`` check before delegating to the plain
``jax.jit`` callable, and the telemetry step hook is the same check —
with the watch disabled the JSONL sink is byte-identical to a run
without this module. Enable with ``MXNET_COMPILE_WATCH=1`` (picked up
at wrapper creation and at ``telemetry.start()``) or explicitly via
:func:`enable`.

Safety valve: the staged ``Compiled`` executable is stricter than
``jax.jit`` (it will not re-specialize). The signature key covers
shape/dtype/weak-type/sharding, so a mismatch should never happen —
but if a staged call ever fails where the plain path would not, the
wrapper permanently falls back to its ``jax.jit`` twin for that
function and counts the degradation, instead of killing the job it
observes.
"""
from __future__ import annotations

import threading
import time
import warnings
from collections import deque

from . import compile_cache, envs

__all__ = ["enabled", "enable", "disable", "reset", "maybe_enable",
           "jit", "stats", "site_stats", "recent_mfu", "peak_table",
           "dtype_peak_factor", "describe_arrays", "step_reset",
           "run_reset", "WatchedFunction"]

_lock = threading.Lock()
_watch = None          # the active _Watch; module-global None check


# ---------------------------------------------------------------------------
# peak-performance tables
# ---------------------------------------------------------------------------

# Peak FLOP/s per chip (bf16 MXU peak for TPUs — public chip specs).
# CPU has no meaningful single number; the placeholder below keeps the
# MFU math defined and is expected to be overridden via
# MXNET_DEVICE_PEAK_FLOPS for any real CPU measurement.
PEAK_FLOPS = {
    "TPU v2": 45e12, "TPU v3": 123e12, "TPU v4": 275e12,
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5p": 459e12,
    "TPU v6 lite": 918e12, "TPU v6e": 918e12,
    "cpu": 1e11,
}

# Peak HBM (or DRAM) bandwidth, bytes/s per chip.
PEAK_BW = {
    "TPU v2": 700e9, "TPU v3": 900e9, "TPU v4": 1228e9,
    "TPU v5 lite": 819e9, "TPU v5e": 819e9, "TPU v5p": 2765e9,
    "TPU v6 lite": 1638e9, "TPU v6e": 1638e9,
    "cpu": 50e9,
}

# Relative achievable peak by COMPUTE dtype, against the tables' bf16
# MXU numbers: fp32 matmuls run as multi-pass bf16 on the MXU (half
# rate as the documented convention here), fp64 is emulated, and int8
# rides the double-rate path newer generations expose. A program's
# compute dtype is the NARROWEST float in its argument signature —
# a mixed-precision program's matmuls run in its low dtype while the
# fp32 master weights ride along element-wise (int8 only when no
# float argument exists: a quantized graph's range scalars ride fp32
# and must not mask wider compute). MFU is normalized per program by
# this factor, so one bf16 AMP step and one fp32 step of the same
# model report comparable utilization instead of the fp32 run
# appearing to waste half the hardware it never had.
PEAK_DTYPE_FACTOR = {
    "float64": 0.25, "float32": 0.5,
    "float16": 1.0, "bfloat16": 1.0,
    "int8": 2.0,
}


def dtype_peak_factor(dtype):
    """The per-dtype peak factor the MFU math uses (1.0 for unknown
    dtypes). Importable by benchmarks — one dtype convention tree-wide."""
    return PEAK_DTYPE_FACTOR.get(str(dtype), 1.0)


_DTYPE_WIDTH = {"float64": 3, "float32": 2, "bfloat16": 1,
                "float16": 1}


def _key_compute_dtype(key):
    """The compute dtype of one argument-signature key: the narrowest
    float among array leaves, else int8 when only int8 arrays flow,
    else None (integer-only programs run no MXU math worth scaling)."""
    narrowest = None
    saw_int8 = False
    for sig in key:
        if len(sig) != 4 or not isinstance(sig[1], str):
            continue                   # python-scalar leaf
        dt = sig[1]
        if dt == "int8":
            saw_int8 = True
        elif dt in _DTYPE_WIDTH and (
                narrowest is None
                or _DTYPE_WIDTH[dt] < _DTYPE_WIDTH[narrowest]):
            narrowest = dt
    if narrowest is not None:
        return narrowest
    return "int8" if saw_int8 else None


def _lookup_peak(table, kind, platform):
    if kind in table:
        return table[kind]
    for k, v in table.items():
        if k != "cpu" and (kind.startswith(k) or k.startswith(kind)):
            return v
    if platform != "cpu" and kind not in _warned_kinds:
        # unknown accelerator: there is no honest builtin — fall back
        # to the placeholder row and tell the operator once to pin the
        # real peak via the env overrides
        _warned_kinds.add(kind)
        warnings.warn(
            "compile_watch: no builtin peak table entry for device "
            "kind %r; using the placeholder row — set "
            "MXNET_DEVICE_PEAK_FLOPS/MXNET_DEVICE_PEAK_BW for "
            "meaningful MFU/BW figures" % kind)
    return table["cpu"]


_warned_kinds = set()


def peak_table():
    """The (per-device peak FLOP/s, peak bytes/s, device kind, device
    count) the MFU math uses — env overrides applied. Importable by
    benchmarks so there is exactly one peak table in the tree."""
    import jax
    devices = jax.local_devices()
    kind = devices[0].device_kind if devices else "cpu"
    platform = devices[0].platform if devices else "cpu"
    flops = envs.get_float("MXNET_DEVICE_PEAK_FLOPS") or \
        _lookup_peak(PEAK_FLOPS, kind, platform)
    bw = envs.get_float("MXNET_DEVICE_PEAK_BW") or \
        _lookup_peak(PEAK_BW, kind, platform)
    return float(flops), float(bw), kind, max(1, len(devices))


# ---------------------------------------------------------------------------
# watch state
# ---------------------------------------------------------------------------

class _Watch:
    """All compile/utilization accumulators. Mutation under the module
    lock; the telemetry callbacks never run while this lock is held
    (lock order: telemetry._lock → compile_watch._lock, never the
    reverse)."""

    def __init__(self):
        self.t0 = time.time()
        self.compile_count = 0
        self.compile_total_s = 0.0
        self.cache_hits = 0      # programs loaded from the disk cache
        self.cache_hit_s = 0.0   # (deserialize time, not XLA compiles)
        self.programs = {}      # site -> per-program dict
        self.storms = []        # [{"program","arg","compiles","steps"}]
        self.degraded = 0       # staged calls that fell back to jit
        self.dispatches = 0     # watched compiled-call executions
        self.site_last = {}     # site -> (flops, bytes) of the most
                                # recent dispatch (metering attribution)
        # current-step accumulators, drained by the telemetry step hook
        self.step_flops = 0.0
        self.step_flops_norm = 0.0   # dtype-factor-normalized flops
        self.step_bytes = 0.0
        self.step_dispatches = 0
        self.step_compiles = 0
        self.step_compile_s = 0.0
        # whole-run utilization accumulators
        self.total_flops = 0.0
        self.total_bytes = 0.0
        self.mfu_ring = deque(maxlen=max(
            1, envs.get_int("MXNET_TELEMETRY_RING")))
        self.bw_ring = deque(maxlen=self.mfu_ring.maxlen)
        self.storm_k = max(2, envs.get_int("MXNET_COMPILE_STORM_K"))
        self.storm_steps = max(
            1, envs.get_int("MXNET_COMPILE_STORM_STEPS"))
        self.peak_flops, self.peak_bw, self.device_kind, self.n_devices \
            = peak_table()

    def program(self, site, statics):
        """Per-program state. Identity is (site, statics): two watched
        functions with different STATIC configuration (an op's attrs,
        a fused step's guard/optimizer key) are different programs by
        design — a compile of each is specialization, not churn —
        while the same site+statics recompiling on argument signature
        IS churn. stats() re-aggregates per site for reporting."""
        key = (site, statics)
        p = self.programs.get(key)
        if p is None:
            p = self.programs[key] = {
                "site": site, "count": 0, "total_s": 0.0,
                "last_desc": None, "causes": {}, "recent": deque(),
                "warned": False, "churn": {}}
        return p


def enabled():
    """True while the compile watch is active."""
    return _watch is not None


def enable():
    """Turn the watch on (idempotent). Reads the storm/peak env knobs
    and registers the per-step utilization probe with telemetry."""
    global _watch
    with _lock:
        if _watch is None:
            _watch = _Watch()
    from . import telemetry
    telemetry._util_probe = _step_probe
    telemetry._util_reset = step_reset
    compile_cache.maybe_enable()   # MXNET_COMPILE_CACHE_DIR rides too
    return _watch


def disable():
    """Turn the watch off; watched functions fall back to their plain
    ``jax.jit`` twins (already-compiled signatures are kept)."""
    global _watch
    from . import telemetry
    telemetry._util_probe = None
    telemetry._util_reset = None
    with _lock:
        _watch = None


def reset():
    """disable() + forget nothing else (wrappers keep their compiled
    caches — recompiling identical programs would distort the very
    compile accounting this module exists for)."""
    disable()


def maybe_enable():
    """Enable when MXNET_COMPILE_WATCH asks for it (called at wrapper
    creation and from ``telemetry.start``). Returns True when active
    after the call."""
    if _watch is not None:
        return True
    if envs.get_bool("MXNET_COMPILE_WATCH"):
        enable()
        return True
    return False


# ---------------------------------------------------------------------------
# argument signatures
# ---------------------------------------------------------------------------

def _leaf_sig(leaf):
    """Hashable compile-relevant signature of one argument leaf: shape,
    dtype, weak-type, and sharding (device placement re-specializes a
    compile exactly like a shape change does)."""
    shape = getattr(leaf, "shape", None)
    if shape is None:
        # python scalar: jit weak-types it by python type
        return ("py", type(leaf).__name__)
    aval = getattr(leaf, "aval", None)
    weak = bool(getattr(aval, "weak_type", False))
    sharding = getattr(leaf, "sharding", None)
    try:
        hash(sharding)
    except TypeError:
        sharding = str(sharding)
    return (tuple(shape), str(getattr(leaf, "dtype", "?")), weak,
            sharding)


def _short_sig(leaf):
    """Human form of a leaf signature: ``f32[32,784]``."""
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return type(leaf).__name__
    dt = str(getattr(leaf, "dtype", "?"))
    dt = {"float32": "f32", "float64": "f64", "float16": "f16",
          "bfloat16": "bf16", "int32": "i32", "int64": "i64",
          "uint32": "u32", "uint8": "u8", "bool": "pred"}.get(dt, dt)
    return "%s[%s]" % (dt, ",".join(str(d) for d in shape))


def describe_arrays(names, arrays):
    """name -> short signature dict for a flat array list (call-site
    helper for the ``describe`` hook)."""
    out = {}
    for i, a in enumerate(arrays):
        n = names[i] if names is not None and i < len(names) \
            else "arg%d" % i
        out[str(n)] = _short_sig(a)
    return out


def _default_describe(args):
    """Generic description when the call site gave none: tree-flatten
    the args and label leaves by positional path."""
    import jax
    leaves = jax.tree_util.tree_leaves(args)
    return {"arg%d" % i: _short_sig(v) for i, v in enumerate(leaves)}


def _diff_desc(old, new):
    """(cause, churning-arg names) between two description dicts.
    Names are kept whole — "aux:moving_mean" must not collapse to
    "aux" — so churn attribution points at the actual tensor. Only
    arguments present on BOTH sides with a different signature count
    as churn; a different argument SET means a different model was
    bound at this site (ensemble/sweep), which is setup, not churn."""
    if old is None:
        return "first_compile", []
    modified = []                    # (full name, human detail)
    reshaped = []
    for name in new:
        if name not in old:
            reshaped.append("%s: new %s" % (name, new[name]))
        elif old[name] != new[name]:
            modified.append((name, "%s: %s -> %s"
                             % (name, old[name], new[name])))
    for name in old:
        if name not in new:
            reshaped.append("%s: removed" % name)
    if modified:
        names = [n for n, _ in modified]
        shown = [d for _, d in modified[:3]]
        if len(modified) > 3:
            shown.append("+%d more" % (len(modified) - 3))
        return "changed " + "; ".join(shown), names
    if reshaped:
        return "rebound " + "; ".join(reshaped[:3]), []
    # identical description but a different full key (sharding or
    # weak-type nuance the short form hides) or a fresh wrapper for
    # the same logical program (an executor rebind)
    return "rebind_or_placement", []


# ---------------------------------------------------------------------------
# cost / memory analysis
# ---------------------------------------------------------------------------

def _cost_of(compiled):
    """(flops, bytes_accessed) from the executable's own cost model;
    zeros when the backend offers none."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return (float(ca.get("flops", 0.0) or 0.0),
                float(ca.get("bytes accessed", 0.0) or 0.0))
    except Exception:
        return 0.0, 0.0


def _memory_of(compiled):
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return None
        out = {}
        for k in ("generated_code_size_in_bytes",
                  "argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "alias_size_in_bytes"):
            v = getattr(ma, k, None)
            if v:
                out[k.replace("_in_bytes", "")] = int(v)
        return out or None
    except Exception:
        return None


# ---------------------------------------------------------------------------
# the watched jit wrapper
# ---------------------------------------------------------------------------

_donation_warned = False


def _warn_donation_stripped(site):
    """One-time, discoverable record of the compile-cache/donation
    tradeoff: a job that OOMs after MXNET_COMPILE_CACHE_DIR was set
    must be able to connect the dots from its own logs/telemetry, not
    from a source comment."""
    global _donation_warned
    from . import telemetry
    telemetry.note("compile_cache_donation_stripped")
    if _donation_warned:
        return
    _donation_warned = True
    warnings.warn(
        "compile_cache: buffer donation is disabled while the "
        "persistent compile cache is active (first affected program: "
        "%r) — donated buffers and deserialized executables do not "
        "mix. Expect one extra transient copy of donated buffers "
        "(params/optimizer state) per step; unset "
        "MXNET_COMPILE_CACHE_DIR if device memory is tighter than "
        "restart time." % site)


class WatchedFunction:
    """A ``jax.jit`` twin that stages compilation explicitly when the
    watch is on. Callable exactly like the jitted function (positional
    args only — every framework site is positional)."""

    __slots__ = ("_jitted", "_site", "_describe", "_cache", "_mu",
                 "_broken", "_counter", "_statics", "_storm", "_opts",
                 "_ctoken", "_csite", "_cache_ok", "_donated")

    def __init__(self, fn, site, describe=None, counter=None,
                 statics=None, storm=True, cache=True,
                 cache_token=None, cache_site=None, **jit_kwargs):
        import jax
        # donation and the persistent disk cache do not mix: donated
        # buffers flowing BETWEEN deserialized executables intermit-
        # tently corrupt the heap (observed on the CPU PJRT client —
        # wrong values, then free()/segfault at teardown). With the
        # cache active at wrapper creation, the program compiles
        # WITHOUT donation — a bounded transient-memory cost the
        # operator traded for restart speed; donation is an
        # optimization, never semantics, so results are unchanged.
        # A donating wrapper (cache enabled later) never touches disk.
        self._donated = bool(jit_kwargs.get("donate_argnums"))
        if self._donated and cache and compile_cache.enabled():
            jit_kwargs = {k: v for k, v in jit_kwargs.items()
                          if k != "donate_argnums"}
            self._donated = False
            _warn_donation_stripped(site)
        self._jitted = jax.jit(fn, **jit_kwargs)
        self._site = site
        self._describe = describe
        self._counter = counter      # extra profiler counter for
        self._cache = {}             # compile ms at this site
        self._statics = statics      # program identity = (site, statics)
        self._storm = bool(storm)    # storm-track this program?
        # the jit options are part of the COMPILED program's identity
        # (donation, out_shardings, compiler options) — they join the
        # persistent-cache key so an option flip is a natural miss
        self._opts = repr(sorted(jit_kwargs.items(), key=lambda kv:
                                 kv[0])) if jit_kwargs else None
        # persistent-cache participation: ``cache_token`` carries the
        # CONTENT this program closes over (a symbol-graph hash, an
        # artifact digest) — site + statics + signature alone cannot
        # distinguish two different models with identical shapes;
        # ``cache_site`` overrides the on-disk site component when the
        # display site embeds a process-local counter; ``cache=False``
        # opts a program whose content has no stable fingerprint (an
        # arbitrary user callable) out of the disk cache entirely
        self._ctoken = cache_token
        self._csite = cache_site or site
        self._cache_ok = bool(cache)
        self._mu = threading.Lock()
        self._broken = False

    @property
    def site(self):
        return self._site

    def __call__(self, *args, **kwargs):
        w = _watch
        if (w is None and (compile_cache._cache is None
                           or not self._cache_ok)) \
                or self._broken or kwargs:
            # the persistent disk cache rides the same staged path, so
            # it works with or without the watch's accounting — a
            # serving replica with only MXNET_COMPILE_CACHE_DIR set
            # still warms from disk
            return self._jitted(*args, **kwargs)
        return self._watched_call(w, args)

    # -- watched path ------------------------------------------------------
    def _watched_call(self, w, args):
        import jax
        try:
            leaves = jax.tree_util.tree_leaves(args)
            if any(isinstance(a, jax.core.Tracer) for a in leaves):
                # called under an outer trace (a caller composing this
                # program into its own jit): staging is meaningless
                # there — the outer program owns the compile
                return self._jitted(*args)
            key = tuple(_leaf_sig(a) for a in leaves)
        except Exception:
            return self._jitted(*args)
        entry = self._cache.get(key)
        if entry is None:
            entry = self._compile(w, key, args)
            if entry is None:        # staging failed: degraded fallback
                return self._jitted(*args)
        out = entry["fn"](*args)
        if w is not None:
            _accrue(w, entry["flops"], entry["flops_norm"],
                    entry["bytes"], self._site)
        return out

    def _compile(self, w, key, args):
        # the whole staging runs under the wrapper's own lock: two
        # threads racing on the same signature (decode-pool workers
        # hitting a shared eager-op wrapper) must produce ONE compile,
        # one record, one storm-clock entry — not N duplicates
        from_disk = False
        with self._mu:
            entry = self._cache.get(key)
            if entry is not None:
                return entry
            ckey = None
            compiled = None
            t0 = time.perf_counter()
            if self._cache_ok and not self._donated \
                    and compile_cache.enabled():
                ckey = compile_cache.entry_key(
                    self._csite, self._statics, key,
                    (self._opts, self._ctoken))
                # deserialize-before-compile: a hit means the
                # executable came off disk — no XLA compile happened,
                # and none is recorded as fresh (the warm-restart
                # zero-fresh-compiles oracle)
                compiled = compile_cache.lookup(ckey)
                from_disk = compiled is not None
            if compiled is None:
                try:
                    compiled = self._jitted.lower(*args).compile()
                except Exception:
                    # never let the observability layer change what
                    # the program raises: re-run through the plain jit
                    # twin (a genuinely bad call re-raises identically;
                    # a staging-only failure permanently degrades this
                    # wrapper instead of the job)
                    self._broken = True
                    if w is not None:
                        with _lock:
                            w.degraded += 1
                    warnings.warn(
                        "compile_watch: staged compile failed for %r; "
                        "falling back to plain jax.jit for this "
                        "program (compile accounting degraded)"
                        % self._site)
                    return None
                if ckey is not None:
                    # serialize-after-compile, off the hot thread
                    compile_cache.store(ckey, compiled)
            dur = time.perf_counter() - t0
            flops, nbytes = _cost_of(compiled)
            mem = None if from_disk else _memory_of(compiled)
            try:
                desc = self._describe(*args) \
                    if self._describe is not None \
                    else _default_describe(args)
            except Exception:
                desc = _default_describe(args)
            cdtype = _key_compute_dtype(key)
            factor = dtype_peak_factor(cdtype) if cdtype else 1.0
            entry = {"fn": compiled, "flops": flops, "bytes": nbytes,
                     "flops_norm": flops / factor, "dtype": cdtype}
            self._cache[key] = entry
        if w is None:
            # cache-only mode (no watch): the disk counters already
            # ticked; there is no compile accounting to fold into
            return entry
        if from_disk:
            event = _record_cache_hit(w, self._site, self._statics,
                                      dur, desc)
        else:
            event = _record_compile(w, self._site, self._statics,
                                    self._storm, dur, desc, flops,
                                    nbytes, mem)
            if cdtype is not None:
                event["compute_dtype"] = cdtype
            if ckey is not None:
                event["cache"] = "miss"
            if self._counter:
                from . import profiler
                profiler.increment_counter(self._counter, dur * 1e3)
        _emit_compile_record(event)
        return entry


def jit(fn, site, describe=None, counter=None, statics=None,
        storm=True, cache=True, cache_token=None, cache_site=None,
        **jit_kwargs):
    """Wrap ``fn`` exactly like ``jax.jit(fn, **jit_kwargs)`` but
    observable: ``site`` names the logical program (recompiles of the
    same (site, statics) identity are diffed/storm-tracked across
    wrapper instances — executor rebinds included), ``describe(*args)
    -> {arg_name: short_sig}`` names arguments for the recompile-cause
    diff, ``counter`` optionally mirrors compile milliseconds into a
    ``profiler.counters()`` entry, and ``storm=False`` opts a
    polymorphic-by-design program (the eager micro-op jits: ``_copy``
    over every param shape is specialization, not churn) out of the
    storm warning while keeping its compiles in the log.

    Persistent-cache contract (``mxnet_tpu.compile_cache``): the disk
    key is (cache_site or site, statics, full argument signature, jit
    options, cache_token, jax/device versions). A site whose program
    closes over content the key cannot see MUST pass ``cache_token``
    (e.g. a symbol-graph hash) or ``cache=False`` — otherwise two
    different models with identical shapes would share an entry."""
    maybe_enable()
    compile_cache.maybe_enable()   # MXNET_COMPILE_CACHE_DIR rides too
    return WatchedFunction(fn, site, describe=describe, counter=counter,
                           statics=statics, storm=storm, cache=cache,
                           cache_token=cache_token,
                           cache_site=cache_site, **jit_kwargs)


# ---------------------------------------------------------------------------
# accounting
# ---------------------------------------------------------------------------

def _accrue(w, flops, flops_norm, nbytes, site=None):
    # run totals accrue at the step boundary (the probe), not here, so
    # they mean "work attributed to this run's steps" — backlog dropped
    # by step_reset() never counts
    with _lock:
        w.dispatches += 1
        w.step_dispatches += 1
        w.step_flops += flops
        w.step_flops_norm += flops_norm
        w.step_bytes += nbytes
        if site is not None:
            w.site_last[site] = (flops, nbytes)


def _step_clock(w):
    """The storm window's clock: telemetry steps when a run is active,
    watched dispatches otherwise (a bare churn loop with no telemetry
    still storms)."""
    from . import telemetry
    run = telemetry._run
    if run is not None:
        return run.steps
    return w.dispatches


def _record_compile(w, site, statics, storm_track, dur, desc, flops,
                    nbytes, mem):
    """Fold one compile into the program's stats (under the lock) and
    return the JSONL-ready event dict. The storm check runs here; the
    warning itself fires outside the lock."""
    storm = None
    clock = _step_clock(w)
    with _lock:
        w.compile_count += 1
        w.compile_total_s += dur
        w.step_compiles += 1
        w.step_compile_s += dur
        p = w.program(site, statics)
        p["count"] += 1
        p["total_s"] += dur
        cause, changed = _diff_desc(p["last_desc"], desc)
        p["last_desc"] = desc
        ckey = cause.split(" ", 1)[0]
        p["causes"][ckey] = p["causes"].get(ckey, 0) + 1
        for n in changed:
            p["churn"][n] = p["churn"].get(n, 0) + 1
        # only argument-churn compiles count toward the storm window:
        # first compiles and rebinds (an ensemble binding N models, an
        # eval clone) are setup cost, not an unpadded input loop
        if changed:
            p["recent"].append(clock)
        while p["recent"] and clock - p["recent"][0] > w.storm_steps:
            p["recent"].popleft()
        if storm_track and changed and len(p["recent"]) >= w.storm_k \
                and not p["warned"]:
            p["warned"] = True
            arg = max(p["churn"], key=p["churn"].get)
            storm = {"program": site, "arg": arg,
                     "compiles": len(p["recent"]),
                     "window_steps": w.storm_steps}
            w.storms.append(storm)
        seq = p["count"]
    if storm is not None:
        warnings.warn(
            "compile_watch: recompile storm — program '%s' compiled "
            "%d times within %d steps; argument '%s' keeps changing "
            "shape/dtype. Pad or bucket it (each distinct signature "
            "is a full XLA compile)."
            % (storm["program"], storm["compiles"],
               storm["window_steps"], storm["arg"]), stacklevel=3)
        from . import telemetry
        telemetry.note("compile_storms")
    event = {"type": "compile", "program": site, "n": seq,
             "dur_ms": round(dur * 1e3, 3), "cause": cause}
    if changed:
        event["changed"] = list(changed)
    if flops:
        event["flops"] = flops
    if nbytes:
        event["bytes"] = nbytes
    if mem:
        event["memory"] = mem
    return event


def _record_cache_hit(w, site, statics, dur, desc):
    """Fold one persistent-cache hit into the program's stats: the
    program exists (so the site shows up in reports) but its fresh
    ``count`` stays untouched — ``site_stats`` counting zero fresh
    compiles on a warm restart IS the cache's acceptance oracle. The
    hit sets ``last_desc`` so a later genuine recompile diffs against
    the signature actually loaded, and never ticks the storm clock
    (loading from disk is the opposite of churn)."""
    with _lock:
        w.cache_hits += 1
        w.cache_hit_s += dur
        p = w.program(site, statics)
        p["cache_hits"] = p.get("cache_hits", 0) + 1
        p["last_desc"] = desc
    return {"type": "compile", "program": site,
            "dur_ms": round(dur * 1e3, 3), "cause": "disk_cache",
            "cache": "hit"}


def _emit_compile_record(event):
    """Append the compile event to the active telemetry run (no-op
    without one) and, when tracing is on, render it as a duration
    event on the trace's ``compile`` track (ts backdated by the
    compile's own duration). Called with NO compile_watch lock held."""
    from . import telemetry, tracing
    telemetry.external_record(event)
    if tracing._tracer is not None:
        dur_s = event.get("dur_ms", 0.0) / 1e3
        args = {"program": event.get("program"),
                "cause": event.get("cause")}
        if event.get("changed"):
            args["changed"] = event["changed"]
        tracing.add("compile:%s" % event.get("program"), "compile",
                    tracing.now() - dur_s, dur_s,
                    tid=tracing.track("compile"), args=args)


def step_reset():
    """Drop anything accrued OUTSIDE an open telemetry step (warmup
    dispatches, init work between runs) — telemetry calls this at
    ``step_begin`` so a step's utilization reflects only its own
    dispatches, never a pre-step backlog that would push MFU past
    100%. No-op when the watch is off."""
    w = _watch
    if w is None:
        return
    with _lock:
        w.step_flops = 0.0
        w.step_flops_norm = 0.0
        w.step_bytes = 0.0
        w.step_dispatches = 0
        w.step_compiles = 0
        w.step_compile_s = 0.0


def run_reset():
    """Re-scope the utilization accumulators to a fresh telemetry run
    (called from ``telemetry.start``): the MFU/BW rings and the
    flops/bytes totals describe THIS run in its summary, not the
    process's lifetime — compile counts/seconds stay lifetime (program
    identity outlives runs) and are run-scoped via the start()
    baseline instead."""
    w = _watch
    if w is None:
        return
    with _lock:
        w.mfu_ring.clear()
        w.bw_ring.clear()
        w.total_flops = 0.0
        w.total_bytes = 0.0
        w.step_flops = 0.0
        w.step_flops_norm = 0.0
        w.step_bytes = 0.0
        w.step_dispatches = 0
        w.step_compiles = 0
        w.step_compile_s = 0.0


def _step_probe(step_seq, dur_s):
    """telemetry's per-step hook (installed by :func:`enable`): drain
    the step accumulators into a ``utilization`` record dict, or None
    when this step dispatched nothing watched. Runs under telemetry's
    lock — must not call back into telemetry."""
    w = _watch
    if w is None:
        return None
    with _lock:
        flops = w.step_flops
        flops_norm = w.step_flops_norm
        nbytes = w.step_bytes
        dispatches = w.step_dispatches
        compiles = w.step_compiles
        compile_s = w.step_compile_s
        w.step_flops = 0.0
        w.step_flops_norm = 0.0
        w.step_bytes = 0.0
        w.step_dispatches = 0
        w.step_compiles = 0
        w.step_compile_s = 0.0
        if dispatches == 0 and compiles == 0:
            return None
        w.total_flops += flops
        w.total_bytes += nbytes
        rec = {"dispatches": dispatches}
        if dur_s > 0 and flops:
            # normalized flops measure each program against ITS
            # dtype's achievable peak (PEAK_DTYPE_FACTOR): a pure-bf16
            # step divides by the full table peak, a pure-fp32 step by
            # half of it, a mixed step by the flop-weighted blend
            mfu = flops_norm / (dur_s * w.peak_flops * w.n_devices)
            rec["flops"] = flops
            if flops_norm != flops:
                rec["flops_norm"] = flops_norm
            # 6 SIGNIFICANT digits: CPU-scale MFUs live around 1e-5,
            # where fixed decimal rounding would destroy the value
            rec["mfu"] = float("%.6g" % mfu)
            w.mfu_ring.append(mfu)
        if dur_s > 0 and nbytes:
            bwu = nbytes / (dur_s * w.peak_bw * w.n_devices)
            rec["bytes"] = nbytes
            rec["bw_util"] = float("%.6g" % bwu)
            w.bw_ring.append(bwu)
        if compiles:
            rec["compiles"] = compiles
            rec["compile_ms"] = round(compile_s * 1e3, 3)
        return rec


# ---------------------------------------------------------------------------
# queries
# ---------------------------------------------------------------------------

def recent_mfu(n=None):
    """Mean MFU over the last ``n`` utilization-carrying steps (None
    when the watch is off or nothing has been measured) — the
    Speedometer's extra column."""
    w = _watch
    if w is None:
        return None
    with _lock:
        vals = list(w.mfu_ring)
    if n:
        vals = vals[-int(n):]
    if not vals:
        return None
    return sum(vals) / len(vals)


def stats():
    """Snapshot of everything: compile counts/seconds per program,
    causes, storms, utilization aggregates, the peak table in use.
    None when the watch is off."""
    w = _watch
    if w is None:
        return None
    from .telemetry import percentile
    with _lock:
        programs = {}
        for p in w.programs.values():
            # aggregate the (site, statics) identities back to the
            # site for reporting: one table row per logical call site
            agg = programs.get(p["site"])
            if agg is None:
                agg = programs[p["site"]] = {
                    "count": 0, "total_s": 0.0, "causes": {},
                    "specializations": 0}
            agg["count"] += p["count"]
            agg["total_s"] = round(agg["total_s"] + p["total_s"], 6)
            agg["specializations"] += 1
            if p.get("cache_hits"):
                agg["cache_hits"] = agg.get("cache_hits", 0) \
                    + p["cache_hits"]
            for k, v in p["causes"].items():
                agg["causes"][k] = agg["causes"].get(k, 0) + v
            if p["churn"]:
                churn = agg.setdefault("churn", {})
                for k, v in p["churn"].items():
                    churn[k] = churn.get(k, 0) + v
        mfu = list(w.mfu_ring)
        bwu = list(w.bw_ring)
        out = {
            "compiles": w.compile_count,
            "compile_total_s": round(w.compile_total_s, 6),
            "cache_hits": w.cache_hits,
            "cache_hit_s": round(w.cache_hit_s, 6),
            "programs": programs,
            "storms": [dict(s) for s in w.storms],
            "dispatches": w.dispatches,
            "degraded": w.degraded,
            "total_flops": w.total_flops,
            "total_bytes": w.total_bytes,
            "device_kind": w.device_kind,
            "n_devices": w.n_devices,
            "peak_flops": w.peak_flops,
            "peak_bw": w.peak_bw,
        }
    if mfu:
        out["mfu"] = {"p50": percentile(mfu, 50),
                      "p90": percentile(mfu, 90),
                      "last": mfu[-1], "samples": len(mfu)}
    if bwu:
        out["bw_util"] = {"p50": percentile(bwu, 50),
                          "p90": percentile(bwu, 90),
                          "samples": len(bwu)}
    return out


def site_stats(prefix=None):
    """Per-site compile counts — ``{site: {"count", "total_s"}}``,
    optionally filtered to sites starting with ``prefix``. The serving
    tests and ``bench.py --serving`` use this as the bounded-program-
    cache oracle: under any request mix, ``site_stats("serving")``
    must hold exactly the bucket-ladder sites, each compiled once per
    replica device. None when the watch is off."""
    w = _watch
    if w is None:
        return None
    out = {}
    with _lock:
        for p in w.programs.values():
            site = p["site"]
            if prefix is not None and not site.startswith(prefix):
                continue
            agg = out.setdefault(site, {"count": 0, "total_s": 0.0})
            agg["count"] += p["count"]
            agg["total_s"] = round(agg["total_s"] + p["total_s"], 6)
            if p.get("cache_hits"):
                # programs loaded from the persistent disk cache: the
                # site is live but its fresh count stays 0 — the key
                # is only present when hits happened, so cache-less
                # runs keep the historical dict shape exactly
                agg["cache_hits"] = agg.get("cache_hits", 0) \
                    + p["cache_hits"]
    return out


def last_dispatch(site):
    """Cost of the most recent watched dispatch at ``site`` —
    ``{"flops", "bytes"}`` straight from the compiled program's
    ``cost_analysis()`` — or None when the watch is off or the site
    has not dispatched. This is the metering layer's per-program cost
    source: a caller that just ran a program under ``site`` reads the
    dispatch's cost here and attributes each batch row its share.
    With the watch off, metering's FLOP fields stay 0 (token and
    page*second conservation are unaffected)."""
    w = _watch
    if w is None:
        return None
    with _lock:
        c = w.site_last.get(site)
    if c is None:
        return None
    return {"flops": c[0], "bytes": c[1]}


def summary_blocks():
    """The ``compile`` / ``utilization`` blocks telemetry.report()
    embeds in the summary record; (None, None) when the watch is off —
    which is what keeps an off-run's sink byte-identical."""
    s = stats()
    if s is None:
        return None, None
    compile_block = {
        "count": s["compiles"],
        "total_s": s["compile_total_s"],
        "programs": s["programs"],
    }
    if s["storms"]:
        compile_block["storms"] = s["storms"]
    if s["degraded"]:
        compile_block["degraded"] = s["degraded"]
    cache = compile_cache.stats()
    if cache is not None:
        compile_block["cache"] = cache
    util_block = {
        "device_kind": s["device_kind"],
        "n_devices": s["n_devices"],
        "peak_flops": s["peak_flops"],
        "peak_bw": s["peak_bw"],
        "total_flops": s["total_flops"],
        "total_bytes": s["total_bytes"],
    }
    if "mfu" in s:
        util_block["mfu"] = s["mfu"]
    if "bw_util" in s:
        util_block["bw_util"] = s["bw_util"]
    return compile_block, util_block
