"""Async sharded checkpointing + elastic resume.

PR 1 made ``Module.fit`` checkpoints atomic (tmp + ``os.replace``) but
they stayed synchronous single-file host writes: every epoch that lands
a save stalls the step for the full device→host copy + serialize +
write + fsync, and the format cannot express state that is sharded
across a mesh. This module completes that half of the fault-tolerance
story (ROADMAP item 3):

- **Copy-on-snapshot, off the critical path** —
  :meth:`CheckpointManager.save` captures each param/aux buffer as a
  device-side copy: an async dispatch costing no host sync and no D2H
  on the training thread, yet immune to the fused train step later
  DONATING the source buffer to XLA (a bare reference would be read
  after deletion by the writer). The snapshot is enqueued; a background
  writer thread performs the D2H transfer, serialization, checksum,
  write and fsync — the same off-critical-path pattern as
  ``io/pipeline.py``'s placer stage. The in-flight queue is bounded
  (``MXNET_CHECKPOINT_INFLIGHT``, default 2): a slow disk applies
  backpressure to the training loop instead of growing host memory
  without bound. Optimizer state is the one pre-serialized piece (its
  buffers ARE replaced in place per step, so the pickle happens at
  enqueue time, accounted as the blocking snapshot cost).

- **One manifest + per-shard artifacts** — each save writes the
  parameters as per-mesh-position shard files plus a JSON manifest
  (``<prefix>-<epoch>.ckpt.json``) holding every shard's sha256 and
  every parameter's piece layout (shard file, key, global index).
  Shard 0 is named ``<prefix>-<epoch>.params`` and carries every
  whole/replicated entry in the PR 1 single-file key format, so a
  checkpoint saved on one device is **byte-compatible with the legacy
  loader**, and legacy epoch listing/scan keep working unchanged.
  Every file is written tmp + fsync + ``os.replace`` and the manifest
  is written LAST — a SIGKILL mid-save strands at most unreferenced
  tmp/shard files, never a manifest pointing at a torn shard; the
  resume scan (``model.load_latest_valid_checkpoint``) verifies the
  checksums and falls back to the previous epoch on any mismatch.

- **Elastic resume** — :func:`load_arrays` re-assembles each
  parameter's global value from its pieces on the host, so
  :func:`restore_params` can ``jax.device_put`` the result against the
  *current* mesh with ``NamedSharding`` (via
  ``parallel.data_parallel.shard_params``): a run preempted on N
  devices resumes on M devices, sharded or replicated, with the same
  values. ``Module.fit(resume_from_checkpoint=True)`` gets this for
  free — params re-enter through the bound executor's own placement.

- **Observability** — the training thread's blocking share (snapshot +
  enqueue wait, or the whole save in sync mode) runs under the
  existing telemetry ``checkpoint`` phase; the writer thread reports a
  ``checkpoint`` JSONL record per save (bytes, snapshot/serialize/
  write/fsync sub-spans, async vs blocking split, last good epoch)
  rendered by ``tools.diagnose``'s Checkpoint table.

- **Deterministic failure testing** — the writer visits the fault
  sites ``ckpt_write`` (before each file write) and ``ckpt_fsync``
  (before each fsync), so ``MXNET_FAULT_PLAN`` can kill or stall a
  save at an exact file boundary. A failed save — injected or real —
  warns and leaves the previous good checkpoint as the resume point;
  it never kills the training loop it protects.

``MXNET_ASYNC_CHECKPOINT=1`` (default) selects the background writer in
``Module.fit``; ``0`` runs the same subsystem synchronously on the
training thread (identical files, identical trajectory — only the
step-time p99 differs; see ``bench.py --checkpoint-overhead``).
"""
from __future__ import annotations

import hashlib
import io as _io
import json
import logging
import os
import queue
import threading
import time

import numpy as _np

from . import envs
from .base import MXNetError

__all__ = ["CheckpointManager", "async_checkpoint_enabled",
           "manifest_path", "load_manifest", "validate_manifest",
           "latest_manifest_epoch", "load_arrays", "load_param_arrays",
           "restore_params", "save_arrays", "saved_dtype_policy",
           "atomic_write_file", "write_bytes_async", "flush_async_writes"]

_PIECE_SEP = "::piece"       # shard-file key suffix for partial pieces
MANIFEST_FORMAT = 1


def async_checkpoint_enabled():
    """The ``MXNET_ASYNC_CHECKPOINT`` gate (default ON) — re-read per
    fit so benchmarks and tests can toggle it."""
    return envs.get_bool("MXNET_ASYNC_CHECKPOINT")


def _tag(prefix, epoch):
    return "%s-%04d" % (prefix, int(epoch))


def manifest_path(prefix, epoch):
    return _tag(prefix, epoch) + ".ckpt.json"


def _shard_file(prefix, epoch, shard, n_shards):
    """Shard 0 keeps the legacy single-file name so PR 1-era loaders
    (and the epoch scan's ``-NNNN.params`` pattern) read new
    checkpoints; higher mesh positions get their own artifact."""
    if shard == 0:
        return _tag(prefix, epoch) + ".params"
    return "%s.shard%02d-of-%02d.params" % (_tag(prefix, epoch), shard,
                                            n_shards)


# ---------------------------------------------------------------------------
# durable file writes (tmp + fsync + os.replace, fault-injectable)
# ---------------------------------------------------------------------------

def atomic_write_file(fname, payload):
    """The checkpoint write discipline: ``<fname>.tmp`` + fsync +
    ``os.replace``, visiting the ``ckpt_write``/``ckpt_fsync`` fault
    sites so MXNET_FAULT_PLAN can abort or stall a save at an exact
    file boundary. A raised fault leaves at most a ``.tmp`` behind —
    never a live, torn ``fname``."""
    from . import fault
    fault.inject("ckpt_write")
    tmp = fname + ".tmp"
    with open(tmp, "wb") as sink:
        sink.write(payload)
        sink.flush()
        fault.inject("ckpt_fsync")
        os.fsync(sink.fileno())
    os.replace(tmp, fname)


def _sha256(payload):
    return hashlib.sha256(payload).hexdigest()


# ---------------------------------------------------------------------------
# shared single-file background writer (gluon Trainer.save_states)
# ---------------------------------------------------------------------------

_bytes_q = None
_bytes_thread = None
_bytes_lock = threading.Lock()
_bytes_errors = []       # (fname, "Type: msg") since the last flush


def _bytes_writer_loop():
    while True:
        fname, payload = _bytes_q.get()
        try:
            atomic_write_file(fname, payload)
        except Exception as exc:               # noqa: BLE001
            with _bytes_lock:
                _bytes_errors.append(
                    (fname, "%s: %s" % (type(exc).__name__,
                                        str(exc)[:200])))
            logging.getLogger(__name__).warning(
                "checkpoint: background write of %s failed (%s: %s)",
                fname, type(exc).__name__, exc)
        finally:
            _bytes_q.task_done()


def write_bytes_async(fname, payload):
    """Durably write ``payload`` to ``fname`` from the shared
    background writer (bounded queue — same backpressure discipline as
    :class:`CheckpointManager`). The caller already holds a consistent
    byte snapshot, so this is safe for pre-serialized state blobs."""
    global _bytes_q, _bytes_thread
    with _bytes_lock:
        if _bytes_thread is None or not _bytes_thread.is_alive():
            _bytes_q = queue.Queue(
                maxsize=max(1, envs.get_int("MXNET_CHECKPOINT_INFLIGHT")))
            _bytes_thread = threading.Thread(
                target=_bytes_writer_loop, daemon=True,
                name="mxckpt-bytes")
            _bytes_thread.start()
    _bytes_q.put((fname, payload))


def flush_async_writes():
    """Block until every :func:`write_bytes_async` payload landed,
    then raise :class:`MXNetError` naming any writes that failed since
    the last flush — a deferred durable write must not fail silently
    (the synchronous path raises, so the async path surfaces the same
    error here)."""
    q = _bytes_q
    if q is not None:
        q.join()
    with _bytes_lock:
        errors, _bytes_errors[:] = list(_bytes_errors), []
    if errors:
        raise MXNetError(
            "background checkpoint write(s) failed: "
            + "; ".join("%s (%s)" % e for e in errors))


# ---------------------------------------------------------------------------
# snapshot: consistent zero-copy capture of a param roster
# ---------------------------------------------------------------------------

def _snapshot_entry(key, value, flat):
    """Capture one roster entry into ``flat`` without blocking: dense
    NDArrays (and raw jax arrays — e.g. the flat dp-sharded optimizer
    state of ``parallel.grad_sync``) contribute a device-side COPY of
    their buffer — an async dispatch preserving the source's sharding,
    not a host sync. The copy (not a bare reference) matters: the fit
    loop re-points the executor's buffers at these same arrays
    (same-device ``device_put`` aliases), and the fused train step
    then DONATES them to XLA — a reference snapshot would be reading a
    deleted buffer by the time the writer thread serializes it. Sparse
    NDArrays and numpy fall back to a host copy now (their buffers can
    be replaced component-wise)."""
    data = getattr(value, "_data", None)
    if data is not None and getattr(value, "stype", "default") \
            == "default":
        flat[key] = data.copy()       # donation-proof device-side copy
    elif hasattr(value, "addressable_shards"):
        flat[key] = value.copy()      # raw jax array, sharding kept
    elif hasattr(value, "asnumpy"):
        # sparse: reuse the nd.save component layout inside shard 0
        from .ndarray.ndarray import _flatten_entry
        _flatten_entry(key, value, flat)
    else:
        flat[key] = _np.asarray(value)


def snapshot_params(arg_params, aux_params=None, extra=None):
    """A consistent point-in-time capture of ``{'arg:name': buffer}``
    (plus ``aux:``) suitable for handing to the background writer —
    O(#params) reference grabs, no device sync, no host copy for dense
    entries. ``extra`` entries carry their full key verbatim (the
    ``opt:bucketBB.slotS`` sharded-optimizer-state roster rides here;
    its per-device pieces land in the manifest's shard files exactly
    like a sharded parameter's)."""
    flat = {}
    for k, v in (arg_params or {}).items():
        _snapshot_entry("arg:%s" % k, v, flat)
    for k, v in (aux_params or {}).items():
        _snapshot_entry("aux:%s" % k, v, flat)
    for k, v in (extra or {}).items():
        _snapshot_entry(k, v, flat)
    return flat


# ---------------------------------------------------------------------------
# sharded serialization
# ---------------------------------------------------------------------------

def _device_order(mesh_devices):
    """Stable shard numbering: position in the flattened device list."""
    return {d: i for i, d in enumerate(mesh_devices)}


def _spans_processes(sharding):
    """True when a sharding's device set covers more than one process
    (a genuinely global array — only possible on backends with
    cross-process SPMD)."""
    try:
        procs = {getattr(d, "process_index", 0)
                 for d in sharding.device_set}
        return len(procs) > 1
    except Exception:
        return False


def _split_shards(flat, process_index=None):
    """Partition a snapshot into per-mesh-position piece rosters.

    Returns ``(shards, layout, n_shards)`` where ``shards[s]`` maps
    shard-file keys to host numpy arrays and ``layout[key]`` is the
    manifest entry (shape, dtype, pieces). Whole/replicated entries go
    to shard 0 under their plain key (legacy format); an entry sharded
    across devices contributes one piece per distinct index, placed in
    the shard of the device that owns it. The D2H transfer happens
    here — on the caller (writer) thread.

    Multi-process mode (``process_index`` given): the LAYOUT covers
    every piece — for process-spanning arrays it is derived from the
    sharding's global ``devices_indices_map``, identical on all ranks
    — but ``shards`` materializes only the pieces THIS process's
    devices own; whole/replicated/host entries are owned by rank 0.
    Each rank writes its own shard files and rank 0 writes the
    manifest after the all-shards barrier (:func:`save_arrays`)."""
    shards = {0: {}} if process_index in (None, 0) else {}
    layout = {}
    for key, data in flat.items():
        sharding = getattr(data, "sharding", None)
        addressable = getattr(data, "addressable_shards", None)
        pieces = []
        if sharding is not None and addressable is not None \
                and process_index is not None \
                and _spans_processes(sharding) \
                and not getattr(data, "is_fully_replicated", True):
            # global (cross-process) array: layout from the global
            # index map — every rank computes the same table; only
            # locally-owned pieces materialize bytes
            order = _device_order(list(sharding.mesh.devices.flat)) \
                if hasattr(sharding, "mesh") else {}
            local = {p.device: p for p in addressable}
            imap = sharding.devices_indices_map(tuple(data.shape))
            devs = sorted(imap, key=lambda d: order.get(d, 1 << 30))
            seen = {}
            for dev in devs:
                index = tuple(
                    (0 if sl.start is None else int(sl.start),
                     int(dim) if sl.stop is None else int(sl.stop))
                    for sl, dim in zip(imap[dev], data.shape))
                if index in seen:
                    continue          # replicated copy of this piece
                seen[index] = dev
                s = order.get(dev, len(seen) - 1)
                pkey = "%s%s%d" % (key, _PIECE_SEP, len(pieces))
                if dev in local:
                    shards.setdefault(s, {})[pkey] = \
                        _np.asarray(local[dev].data)
                pieces.append({"shard": s, "key": pkey,
                               "index": [list(ix) for ix in index]})
        elif sharding is not None and addressable is not None \
                and len(addressable) > 1 \
                and process_index in (None, 0) \
                and not getattr(data, "is_fully_replicated", True):
            order = _device_order(list(sharding.mesh.devices.flat)) \
                if hasattr(sharding, "mesh") else {}
            seen = set()
            for piece in addressable:
                index = tuple(
                    (0 if sl.start is None else int(sl.start),
                     int(dim) if sl.stop is None else int(sl.stop))
                    for sl, dim in zip(piece.index, data.shape))
                if index in seen:
                    continue          # replicated copy of this piece
                seen.add(index)
                s = order.get(piece.device, len(seen) - 1)
                pkey = "%s%s%d" % (key, _PIECE_SEP, len(pieces))
                shards.setdefault(s, {})[pkey] = _np.asarray(piece.data)
                pieces.append({"shard": s, "key": pkey,
                               "index": [list(ix) for ix in index]})
        if not pieces:
            if process_index in (None, 0):
                shards[0][key] = _np.asarray(data)
            pieces = [{"shard": 0, "key": key, "index": None}]
        if hasattr(data, "shape"):
            layout[key] = {"shape": [int(s) for s in data.shape],
                           "dtype": str(_np.dtype(data.dtype)),
                           "pieces": pieces}
        else:                          # flattened sparse component
            layout[key] = {"pieces": pieces}
    # renumber shard ids densely (sorted device order -> 0..k-1): on a
    # multi-axis mesh the distinct-piece owners need not sit at flat
    # positions 0..k-1, and the manifest shard list, piece references
    # and file names must agree on one contiguous numbering. The map
    # derives from the LAYOUT's piece union (not the locally-
    # materialized shards) so every rank of a multi-process save
    # numbers — and names — its files identically.
    used = sorted({p["shard"] for entry in layout.values()
                   for p in entry["pieces"]} | set(shards))
    pos = {s: i for i, s in enumerate(used)}
    if any(s != i for s, i in pos.items()):
        shards = {pos[s]: roster for s, roster in shards.items()}
        for entry in layout.values():
            for piece in entry["pieces"]:
                piece["shard"] = pos[piece["shard"]]
    return shards, layout, len(used)


def _npz_bytes(arrays):
    buf = _io.BytesIO()
    _np.savez(buf, **arrays)
    return buf.getvalue()


def _process_topology():
    """(process_index, process_count) of the running job — (0, 1) for
    a plain single-process run."""
    try:
        import jax
        return int(jax.process_index()), int(jax.process_count())
    except Exception:
        return 0, 1


def save_arrays(prefix, epoch, flat, states_bytes=None, symbol=None,
                meta=None):
    """Write one sharded checkpoint: shard files first, manifest last.

    ``flat`` is a :func:`snapshot_params` roster. Returns the stats
    dict the telemetry record is built from. Raises on failure (incl.
    planned ``ckpt_write``/``ckpt_fsync`` faults) — the caller decides
    whether that is fatal; the manifest is only ever written after
    every shard it references landed and fsynced.

    ``meta`` is an optional JSON-safe dict recorded verbatim under the
    manifest's ``meta`` key — the AMP dtype policy rides here as
    ``{"dtype_policy": policy.describe()}`` so a checkpoint knows what
    precision it was trained under (loaders that predate the key
    ignore it; the manifest format is unchanged).

    **Multi-process jobs** (a jax.distributed group; every rank calls
    this — SPMD discipline): each rank durably writes the shard files
    its own devices own (rank 0 also owns every whole/replicated
    entry, the symbol and the optimizer states), every rank then meets
    an all-shards coordination barrier, and ONLY rank 0 writes the
    manifest — last, after checksumming every referenced shard file
    (its own from memory, its peers' from the shared filesystem). A
    rank that died mid-epoch fails the barrier on the survivors, so
    the save fails cleanly and the previous manifest stays the resume
    point; a torn shard can never be referenced because the manifest
    postdates every shard fsync."""
    t0 = time.perf_counter()
    me, world = _process_topology()
    shards, layout, n_shards = _split_shards(
        flat, me if world > 1 else None)
    t_snap = time.perf_counter()
    dirname = os.path.dirname(prefix)
    if dirname:
        os.makedirs(dirname, exist_ok=True)

    local_entries = {}
    payloads = []
    total_bytes = 0
    for s in sorted(shards):
        payload = _npz_bytes(shards[s])
        fname = _shard_file(prefix, epoch, s, n_shards)
        local_entries[s] = {"file": os.path.basename(fname),
                            "sha256": _sha256(payload),
                            "bytes": len(payload)}
        payloads.append((fname, payload))
        total_bytes += len(payload)
    t_ser = time.perf_counter()

    if symbol is not None and me == 0:
        symbol.save("%s-symbol.json" % prefix)
    # states BEFORE shards: a kill between the two strands only a
    # .states file (an epoch with no .params is never listed), whereas
    # the reverse order would leave a durable legacy-loadable .params
    # whose missing states the scan accepts — a resume with silently
    # fresh optimizer state
    states_entry = None
    if states_bytes is not None and me == 0:
        states_file = _tag(prefix, epoch) + ".states"
        atomic_write_file(states_file, states_bytes)
        states_entry = {"file": os.path.basename(states_file),
                        "sha256": _sha256(states_bytes),
                        "bytes": len(states_bytes)}
        total_bytes += len(states_bytes)
    for fname, payload in payloads:
        atomic_write_file(fname, payload)
    t_write = time.perf_counter()

    if world > 1:
        # every rank's shards are durable before anyone proceeds; a
        # dead rank fails this barrier (bounded) on the survivors and
        # the save fails cleanly — the old manifest stays good
        from .parallel import multihost
        multihost.barrier("ckpt/%s" % _tag(prefix, epoch))
        if me != 0:
            t_end = time.perf_counter()
            return {"epoch": int(epoch), "bytes": total_bytes,
                    "shards": len(payloads), "manifest": False,
                    "snapshot_ms": round((t_snap - t0) * 1e3, 3),
                    "serialize_ms": round((t_ser - t_snap) * 1e3, 3),
                    "write_ms": round((t_write - t_ser) * 1e3, 3),
                    "manifest_ms": 0.0,
                    "total_ms": round((t_end - t0) * 1e3, 3)}

    shard_entries = []
    for s in range(n_shards):
        entry = local_entries.get(s)
        if entry is None:
            # a peer's shard (shared filesystem): checksum the bytes
            # it fsynced — the manifest must vouch for every file it
            # references, whoever wrote it
            fname = _shard_file(prefix, epoch, s, n_shards)
            if not os.path.isfile(fname):
                raise MXNetError(
                    "checkpoint %s: peer shard %d (%s) missing after "
                    "the all-shards barrier" % (_tag(prefix, epoch),
                                                s, fname))
            with open(fname, "rb") as f:
                payload = f.read()
            entry = {"file": os.path.basename(fname),
                     "sha256": _sha256(payload),
                     "bytes": len(payload)}
        shard_entries.append(entry)

    manifest = {"format": MANIFEST_FORMAT, "epoch": int(epoch),
                "time": time.time(),
                "shards": [dict(e, shard=i)
                           for i, e in enumerate(shard_entries)],
                "params": layout}
    if world > 1:
        manifest["processes"] = world
    if states_entry is not None:
        manifest["optimizer_states"] = states_entry
    if meta:
        manifest["meta"] = dict(meta)
    atomic_write_file(manifest_path(prefix, epoch),
                      json.dumps(manifest, sort_keys=True).encode())
    t_end = time.perf_counter()
    return {"epoch": int(epoch), "bytes": total_bytes,
            "shards": len(shard_entries),
            "snapshot_ms": round((t_snap - t0) * 1e3, 3),
            "serialize_ms": round((t_ser - t_snap) * 1e3, 3),
            "write_ms": round((t_write - t_ser) * 1e3, 3),
            "manifest_ms": round((t_end - t_write) * 1e3, 3),
            "total_ms": round((t_end - t0) * 1e3, 3)}


# ---------------------------------------------------------------------------
# load / validate / elastic restore
# ---------------------------------------------------------------------------

def latest_manifest_epoch(prefix, validate=True):
    """The newest epoch under ``prefix`` whose manifest (and, with
    ``validate``, every artifact it references) checks out — the
    supervised launcher's resume scan (``tools/launch.py --supervise
    --resume-prefix``) and the workers' own restart hook. Torn or
    corrupt epochs are skipped with a warning, exactly like the
    training-side resume scan; returns None when nothing usable
    exists."""
    import glob
    import re
    base = os.path.basename(prefix)
    dirname = os.path.dirname(prefix) or "."
    # \d{4,}, not \d{4}: '%04d' grows past four digits at epoch 10000
    # (the model.py epoch-scan precedent)
    pat = re.compile(re.escape(base) + r"-(\d{4,})\.ckpt\.json$")
    epochs = []
    for path in glob.glob(os.path.join(dirname, base + "-*.ckpt.json")):
        m = pat.match(os.path.basename(path))
        if m:
            epochs.append(int(m.group(1)))
    for epoch in sorted(epochs, reverse=True):
        try:
            if validate:
                validate_manifest(prefix, epoch)
            elif load_manifest(prefix, epoch) is None:
                continue
            return epoch
        except (MXNetError, ValueError, OSError) as exc:
            logging.getLogger(__name__).warning(
                "checkpoint scan: epoch %04d under %s is torn/corrupt "
                "(%s) — skipping", epoch, prefix, exc)
    return None


def load_manifest(prefix, epoch):
    """The parsed manifest for ``(prefix, epoch)``, or None when this
    epoch predates the manifest format (a PR 1-era single file)."""
    path = manifest_path(prefix, epoch)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return json.load(f)


def _read_entry(prefix, epoch, entry, validate=True):
    """Read one manifest artifact's bytes, verifying existence and
    (when ``validate``) its recorded sha256 — raising MXNetError that
    names the missing/torn file. One read serves both the checksum and
    the deserialization."""
    base = os.path.dirname(_tag(prefix, epoch))
    path = os.path.join(base, entry["file"]) if base else entry["file"]
    if not os.path.isfile(path):
        raise MXNetError(
            "checkpoint %s: missing artifact %s"
            % (_tag(prefix, epoch), entry["file"]))
    with open(path, "rb") as f:
        payload = f.read()
    if validate and _sha256(payload) != entry["sha256"]:
        raise MXNetError(
            "checkpoint %s: artifact %s is torn/corrupt "
            "(checksum mismatch)" % (_tag(prefix, epoch),
                                     entry["file"]))
    return payload


def validate_manifest(prefix, epoch, manifest=None):
    """Verify every artifact the manifest references: shard files and
    the optimizer-state sibling must exist and match their recorded
    sha256. Raises MXNetError naming the torn file; returns the
    manifest on success."""
    manifest = manifest if manifest is not None \
        else load_manifest(prefix, epoch)
    if manifest is None:
        raise MXNetError("no manifest for %s" % _tag(prefix, epoch))
    entries = list(manifest["shards"])
    if manifest.get("optimizer_states") is not None:
        entries.append(manifest["optimizer_states"])
    for entry in entries:
        _read_entry(prefix, epoch, entry)
    return manifest


def _restore_dtype(arr, entry):
    """Give a shard-file array back its manifest dtype: npz preserves
    extension dtypes (bf16/fp16 low-precision params) only as raw void
    bytes, so a loaded ``|V2`` buffer is re-viewed as the dtype the
    layout recorded — a zero-copy reinterpretation, bit-exact."""
    want = entry.get("dtype")
    if not want or str(arr.dtype) == want:
        return arr
    dt = _np.dtype(want)
    return arr.view(dt) if arr.dtype.itemsize == dt.itemsize \
        else arr.astype(dt)


def load_arrays(prefix, epoch, validate=True):
    """Load a manifest checkpoint back into a flat ``{'arg:name':
    NDArray}`` host dict, re-assembling sharded entries from their
    pieces. ``validate=True`` (default) checksums every referenced
    artifact (shards AND the optimizer-state sibling) against the same
    bytes it deserializes — one read per file — so torn writes surface
    as MXNetError, exactly what the resume scan catches to fall back
    an epoch."""
    from .ndarray.ndarray import _unflatten
    from . import ndarray as nd
    manifest = load_manifest(prefix, epoch)
    if manifest is None:
        raise MXNetError("no manifest for %s" % _tag(prefix, epoch))
    shard_data = []
    for entry in manifest["shards"]:
        payload = _read_entry(prefix, epoch, entry, validate=validate)
        shard_data.append(dict(_np.load(_io.BytesIO(payload),
                                        allow_pickle=False)))
    if validate and manifest.get("optimizer_states") is not None:
        _read_entry(prefix, epoch, manifest["optimizer_states"])
    whole, out = {}, {}
    for key, entry in manifest["params"].items():
        pieces = entry["pieces"]
        if len(pieces) == 1 and pieces[0]["index"] is None:
            whole[key] = _restore_dtype(
                shard_data[pieces[0]["shard"]][pieces[0]["key"]], entry)
            continue
        full = _np.empty(tuple(entry["shape"]),
                         _np.dtype(entry["dtype"]))
        for p in pieces:
            ix = tuple(slice(a, b) for a, b in p["index"])
            full[ix] = _restore_dtype(shard_data[p["shard"]][p["key"]],
                                      entry)
        out[key] = nd.array(full)
    out.update(_unflatten(whole))
    return out


def load_param_arrays(prefix, epoch, validate=True):
    """Flat ``{name: numpy array}`` of a manifest checkpoint's ``arg``
    parameters (``aux`` entries ride along under their plain names) —
    the decode server's weight hot-swap source
    (``serving.DecodeServer.swap_weights(prefix=..., epoch=...)``).
    Values come back as plain host arrays: placement is the caller's
    (the topology-neutral manifest makes the swap a pure placement
    problem — save on any mesh, serve on any device)."""
    flat = load_arrays(prefix, epoch, validate=validate)
    out = {}
    for key, val in flat.items():
        name = key.split(":", 1)[1] if ":" in key else key
        out[name] = val.asnumpy() if hasattr(val, "asnumpy") \
            else _np.asarray(val)
    return out


def saved_dtype_policy(prefix, epoch):
    """The :class:`~mxnet_tpu.amp.DtypePolicy` a manifest checkpoint
    was saved under (the ``meta.dtype_policy`` record), or None for a
    checkpoint saved without one — pre-AMP manifests and plain fp32
    runs look identical here."""
    from .amp import DtypePolicy
    manifest = load_manifest(prefix, epoch)
    meta = (manifest or {}).get("meta") or {}
    return DtypePolicy.from_describe(meta.get("dtype_policy"))


def restore_params(prefix, epoch, mesh=None, rules=None, validate=True,
                   policy=None):
    """Elastic resume: load ``(arg_params, aux_params)`` from a
    manifest checkpoint and, when ``mesh`` is given, re-place every
    parameter against the *current* mesh via ``jax.device_put`` with
    ``NamedSharding`` (``parallel.data_parallel.shard_params``;
    ``rules`` maps name substrings to PartitionSpecs, default
    replicated). The save-time topology is irrelevant — values are
    re-assembled on the host first, so a 1-device save resumes sharded
    on N devices and vice versa.

    ``policy`` casts every parameter to its per-name resolved dtype on
    the host, BEFORE placement: pass an ``amp.DtypePolicy`` to resume
    under that policy (an AMP checkpoint stores fp32 masters, so any
    resume precision is a cast of the exact master — bit-identical
    wherever dtypes agree), or the string ``"manifest"`` to re-adopt
    whatever policy the checkpoint was saved under (a no-op when none
    was recorded). The save-time and resume-time policies are fully
    decoupled: bf16-trained checkpoints resume fp32 and vice versa."""
    flat = load_arrays(prefix, epoch, validate=validate)
    arg_params, aux_params = {}, {}
    for k, v in flat.items():
        tp, name = k.split(":", 1)
        (arg_params if tp == "arg" else aux_params)[name] = v
    if policy == "manifest":
        policy = saved_dtype_policy(prefix, epoch)
    if policy is not None:
        arg_params = policy.cast_params(arg_params)
        aux_params = policy.cast_params(aux_params)
    if mesh is not None:
        from .parallel.data_parallel import shard_params
        arg_params = shard_params(arg_params, mesh, rules=rules)
        aux_params = shard_params(aux_params, mesh, rules=rules)
    return arg_params, aux_params


# ---------------------------------------------------------------------------
# the manager: bounded-queue background writer
# ---------------------------------------------------------------------------

_CLOSE = object()


class CheckpointManager:
    """Owns one checkpoint prefix's save pipeline for a training loop.

    Async mode (default): ``save()`` snapshots (reference grabs +
    optimizer-state pickle), opens the telemetry ``checkpoint`` span
    only for that blocking part plus any enqueue backpressure wait,
    and returns; a daemon writer thread does D2H + serialize + durable
    writes. Sync mode runs the identical writer code on the calling
    thread. Failed saves warn and leave :attr:`last_good_epoch`
    untouched — checkpointing never kills the run it protects."""

    def __init__(self, prefix, symbol=None, async_=None, inflight=None,
                 logger=None, meta=None):
        self.prefix = prefix
        self._symbol = symbol
        self._symbol_saved = False
        self.meta = dict(meta) if meta else None
        self.async_ = async_checkpoint_enabled() if async_ is None \
            else bool(async_)
        depth = inflight if inflight is not None \
            else envs.get_int("MXNET_CHECKPOINT_INFLIGHT")
        self._q = queue.Queue(maxsize=max(1, int(depth)))
        self._thread = None
        self._lock = threading.Lock()
        self.logger = logger or logging.getLogger(__name__)
        self.last_good_epoch = None
        self.saves = 0
        self.failures = 0
        self.bytes_written = 0
        self._idle = threading.Event()
        self._idle.set()

    # -- public surface ---------------------------------------------------
    def save(self, epoch, arg_params, aux_params=None, states_bytes=None,
             extra=None):
        """Checkpoint ``epoch``. Blocking cost in async mode is the
        snapshot + (only under backpressure) the bounded-queue wait;
        sync mode blocks for the whole durable write. Both run under
        the telemetry ``checkpoint`` phase. ``extra`` rides verbatim
        keys into the shard roster (sharded optimizer state)."""
        from . import telemetry, tracing
        with telemetry.span("checkpoint"):
            t0 = time.perf_counter()
            # causal context captured HERE, on the training thread
            # that triggered the save — the writer thread's trace
            # span parents to this step via the explicit token
            ctx = tracing.context()
            flat = snapshot_params(arg_params, aux_params, extra=extra)
            if not self.async_:
                self._write(epoch, flat, states_bytes, t0,
                            blocking=True, ctx=ctx)
                return
            self._ensure_thread()
            self._idle.clear()
            # bounded put IS the backpressure: a slow disk stalls the
            # trainer here instead of queueing unbounded snapshots.
            # The enqueue time is stamped AFTER put() returns so that
            # stall lands in blocking_ms (the trainer paid it), not
            # async_ms — the writer reads it through the shared dict
            timing = {"t0": t0, "ctx": ctx}
            self._q.put((epoch, flat, states_bytes, timing))
            timing["t_enq"] = time.perf_counter()

    def wait(self):
        """Block until every enqueued save has been written (or
        failed). The post-loop resume scan and tests call this."""
        if self._thread is None:
            return
        self._q.join()
        self._idle.wait()

    def close(self):
        """Drain in-flight saves and stop the writer thread. Safe to
        call twice; the manager can be reused after (a new thread
        starts lazily)."""
        if self._thread is None:
            return
        self._q.join()
        self._idle.wait()
        self._q.put(_CLOSE)
        self._thread.join(timeout=30)
        self._thread = None

    def stats(self):
        with self._lock:
            return {"saves": self.saves, "failures": self.failures,
                    "bytes_written": self.bytes_written,
                    "last_good_epoch": self.last_good_epoch,
                    "async": self.async_}

    # -- writer -----------------------------------------------------------
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer_loop, daemon=True,
                name="mxckpt-write")
            self._thread.start()

    def _writer_loop(self):
        while True:
            item = self._q.get()
            if item is _CLOSE:
                self._q.task_done()
                return
            epoch, flat, states_bytes, timing = item
            try:
                self._write(epoch, flat, states_bytes, timing["t0"],
                            blocking=False,
                            t_enq=timing.get("t_enq"),
                            ctx=timing.get("ctx"))
            finally:
                self._q.task_done()
                if self._q.unfinished_tasks == 0:
                    self._idle.set()

    def _symbol_once(self):
        if self._symbol is not None and not self._symbol_saved:
            self._symbol.save("%s-symbol.json" % self.prefix)
            self._symbol_saved = True

    def _write(self, epoch, flat, states_bytes, t0, blocking,
               t_enq=None, ctx=None):
        """One durable save + its accounting; never raises. ``ctx`` is
        the trace-context token save() captured on the training thread
        — the writer's trace span parents to that step explicitly."""
        from . import telemetry, tracing
        t_work0 = time.perf_counter()
        if t_enq is None and not blocking:
            # writer won the handoff race before save() stamped the
            # enqueue time — the put cannot have blocked, so now is
            # the enqueue time to within the race window
            t_enq = time.perf_counter()
        rec = {"epoch": int(epoch), "async": not blocking}
        try:
            self._symbol_once()
            stats = save_arrays(self.prefix, epoch, flat,
                                states_bytes=states_bytes,
                                meta=self.meta)
            rec.update(stats, ok=True)
            with self._lock:
                self.saves += 1
                self.bytes_written += stats["bytes"]
                if self.last_good_epoch is None \
                        or epoch > self.last_good_epoch:
                    self.last_good_epoch = epoch
        except Exception as exc:               # noqa: BLE001
            with self._lock:
                self.failures += 1
            rec.update(ok=False, error="%s: %s"
                       % (type(exc).__name__, str(exc)[:200]))
            self.logger.warning(
                "checkpoint: save of epoch %d failed (%s: %s) — "
                "last good epoch is %s", epoch, type(exc).__name__,
                exc, self.last_good_epoch)
        now = time.perf_counter()
        if blocking:
            rec["blocking_ms"] = round((now - t0) * 1e3, 3)
            rec["async_ms"] = 0.0
        else:
            rec["blocking_ms"] = round((t_enq - t0) * 1e3, 3)
            rec["async_ms"] = round((now - t_enq) * 1e3, 3)
        rec["last_good_epoch"] = self.last_good_epoch
        if tracing._tracer is not None:
            args = dict(ctx or {})
            args.update(epoch=int(epoch), ok=bool(rec.get("ok")),
                        bytes=rec.get("bytes", 0))
            tracing.add("ckpt:epoch%04d" % int(epoch), "checkpoint",
                        t_work0, now - t_work0,
                        tid=tracing.track("checkpoint"), args=args)
        telemetry.checkpoint_event(rec)
