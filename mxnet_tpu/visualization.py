"""Network visualization (parity: python/mxnet/visualization.py):
print_summary parameter counting + plot_network graphviz export."""
from __future__ import annotations

import json

from .symbol import Symbol

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64,
                                                                  .74, 1.)):
    """Print layer summary with param counts
    (reference: visualization.py:47)."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    show_shape = False
    shape_dict = {}
    if shape is not None:
        show_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise ValueError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    heads = {x[0] for x in conf["heads"]}
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ['Layer (type)', 'Output Shape', 'Param #',
                  'Previous Layer']

    def print_row(fields, positions):
        line = ''
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += ' ' * (positions[i] - len(line))
        print(line)

    print('_' * line_length)
    print_row(to_display, positions)
    print('=' * line_length)

    total_params = 0
    param_counts = _param_counts(symbol, shape)
    for node in nodes:
        op = node["op"]
        name = node["name"]
        if op == "null":
            continue
        out_shape = None
        if show_shape:
            key = name + "_output"
            if key in shape_dict and shape_dict[key]:
                out_shape = shape_dict[key][1:]
        cur_param = param_counts.get(name, 0)
        pre_node = []
        for item in node["inputs"]:
            input_node = nodes[item[0]]
            if input_node["op"] != "null" or item[0] in heads:
                pre_node.append(input_node["name"])
        print_row([name + '(' + op + ')', str(out_shape), cur_param,
                   pre_node[0] if pre_node else ''], positions)
        print('_' * line_length)
        total_params += cur_param
    print("Total params: {params}".format(params=total_params))
    print('_' * line_length)
    return total_params


def _param_counts(symbol, shape):
    counts = {}
    if shape is None:
        return counts
    try:
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape)
    except Exception:
        return counts
    arg_names = symbol.list_arguments()
    data_like = set(shape.keys())
    for name, s in zip(arg_names, arg_shapes):
        if name in data_like or s is None:
            continue
        n = 1
        for d in s:
            n *= d
        # attribute param to its owning layer prefix
        owner = name.rsplit("_", 1)[0]
        counts[owner] = counts.get(owner, 0) + n
    return counts


def plot_network(symbol, title="plot", save_format='pdf', shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Graphviz digraph of the network (reference: visualization.py:211).
    Requires the ``graphviz`` package."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("plot_network requires graphviz library")
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    draw_shape = shape is not None
    shape_dict = {}
    if draw_shape:
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    if node_attrs:
        node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)
    hidden_nodes = set()
    for node in nodes:
        op = node["op"]
        name = node["name"]
        attrs = node.get("attrs", {})
        label = name
        if op == "null":
            if name.endswith("weight") or name.endswith("bias") or \
                    name.endswith("gamma") or name.endswith("beta") or \
                    name.endswith("moving_mean") or \
                    name.endswith("moving_var"):
                if hide_weights:
                    hidden_nodes.add(name)
                continue
            label = name
            color = "#8dd3c7"
        elif op in ("Convolution", "Deconvolution"):
            label = "%s\n%s/%s, %s" % (op, attrs.get("kernel", ""),
                                       attrs.get("stride", "1"),
                                       attrs.get("num_filter", ""))
            color = "#fb8072"
        elif op == "FullyConnected":
            label = "FullyConnected\n%s" % attrs.get("num_hidden", "")
            color = "#fb8072"
        elif op == "BatchNorm":
            color = "#bebada"
        elif op in ("Activation", "LeakyReLU"):
            label = "%s\n%s" % (op, attrs.get("act_type", ""))
            color = "#ffffb3"
        elif op == "Pooling":
            label = "Pooling\n%s, %s/%s" % (attrs.get("pool_type", ""),
                                            attrs.get("kernel", ""),
                                            attrs.get("stride", "1"))
            color = "#80b1d3"
        elif op in ("Concat", "Flatten", "Reshape"):
            color = "#fdb462"
        elif op == "Softmax" or op == "SoftmaxOutput":
            color = "#b3de69"
        else:
            color = "#fccde5"
        dot.node(name=name, label=label, fillcolor=color, **node_attr)
    for node in nodes:
        op = node["op"]
        name = node["name"]
        if op == "null":
            continue
        for item in node["inputs"]:
            input_node = nodes[item[0]]
            input_name = input_node["name"]
            if input_name in hidden_nodes:
                continue
            attrs = {"dir": "back", "arrowtail": "open"}
            if draw_shape:
                key = input_name
                if input_node["op"] != "null":
                    key += "_output"
                if key in shape_dict and shape_dict[key]:
                    attrs["label"] = "x".join(
                        str(x) for x in shape_dict[key][1:])
            dot.edge(tail_name=name, head_name=input_name, **attrs)
    return dot
