"""Bucketed sequence iterators (reference: python/mxnet/rnn/io.py).

``BucketSentenceIter`` feeds ``BucketingModule``: sentences are grouped
into the smallest bucket that fits, padded to the bucket length, and
each batch carries its ``bucket_key`` so the module switches to (or
compiles once) the executor for that length — the strategy that bounds
XLA recompiles for variable-length data (SURVEY §2.2 bucketing row).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc, DataIter
from ..ndarray import array as _nd_array

__all__ = ["BucketSentenceIter", "encode_sentences"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Map tokenised sentences to integer ids, building the vocab as
    needed (reference: rnn/io.py encode_sentences)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    raise MXNetError("word %s not in provided vocab" % word)
                if idx == invalid_label:
                    idx += 1
                vocab[word] = idx
                idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Iterate encoded sentences in length buckets.

    Labels are the data shifted one step left (next-token prediction),
    padded with ``invalid_label`` — the PTB language-model contract.
    """

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32", layout="NT"):
        super().__init__(batch_size=batch_size)
        if not buckets:
            lens = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size and i > 0]
        buckets = sorted(buckets)
        if not buckets:
            raise MXNetError("no usable buckets for the given sentences")

        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.layout = layout
        self.invalid_label = invalid_label
        self.buckets = buckets
        self.default_bucket_key = max(buckets)

        # place each sentence in the smallest bucket that fits
        self.data = [[] for _ in buckets]
        ndiscard = 0
        for sent in sentences:
            pos = np.searchsorted(buckets, len(sent))
            if pos >= len(buckets):
                ndiscard += 1
                continue
            pad = np.full((buckets[pos],), invalid_label, dtype=dtype)
            pad[:len(sent)] = sent
            self.data[pos].append(pad)
        # keep 2-D shape even for buckets no sentence landed in
        self.data = [np.asarray(x, dtype=dtype) if x else
                     np.zeros((0, buckets[i]), dtype=dtype)
                     for i, x in enumerate(self.data)]
        if ndiscard:
            import logging
            logging.warning("BucketSentenceIter discarded %d sentences "
                            "longer than the largest bucket", ndiscard)

        self.batch_axis = layout.find("N")
        shape = (batch_size, self.default_bucket_key) \
            if self.batch_axis == 0 else (self.default_bucket_key,
                                          batch_size)
        self.provide_data = [DataDesc(data_name, shape, layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, layout=layout)]
        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend((i, j) for j in
                            range(0, len(buck) - batch_size + 1,
                                  batch_size))
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        np.random.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)
        # labels: next token; last position gets invalid_label
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            label = np.full_like(buck, self.invalid_label)
            if buck.shape[1] > 1:
                label[:, :-1] = buck[:, 1:]
            self.nddata.append(_nd_array(buck, dtype=self.dtype))
            self.ndlabel.append(_nd_array(label, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        bs = self.batch_size
        if self.batch_axis == 0:
            data = self.nddata[i][j:j + bs]
            label = self.ndlabel[i][j:j + bs]
        else:
            data = self.nddata[i][j:j + bs].T
            label = self.ndlabel[i][j:j + bs].T
        L = self.buckets[i]
        shape = (bs, L) if self.batch_axis == 0 else (L, bs)
        return DataBatch(
            [data], [label], pad=0, bucket_key=L,
            provide_data=[DataDesc(self.data_name, shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, shape,
                                    layout=self.layout)])
