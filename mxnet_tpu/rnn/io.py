"""Bucketed sequence iterators (reference: python/mxnet/rnn/io.py).

``BucketSentenceIter`` feeds ``BucketingModule``: sentences are grouped
into the smallest bucket that fits, padded to the bucket length, and
each batch carries its ``bucket_key`` so the module switches to (or
compiles once) the executor for that length — the strategy that bounds
XLA recompiles for variable-length data (SURVEY §2.2 bucketing row).

Unlike the reference (which silently drops up to ``batch_size - 1``
sentences per bucket every epoch), the final partial batch of each
bucket is **padded mask-aware**: pad rows carry ``invalid_label`` in
both data and label, the batch's ``pad`` field counts them, and the
loss/metric side ignores them through the usual ``ignore_label``
contract (``SoftmaxOutput(use_ignore=True)``,
``metric.Perplexity/Accuracy(ignore_label=...)``). Pad-row and
discarded-sentence counts surface through the cumulative ``bucketing``
telemetry record (``mxnet_tpu.bucketing.record``), rendered by the
diagnose Bucketing table.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc, DataIter
from ..ndarray import array as _nd_array

__all__ = ["BucketSentenceIter", "encode_sentences"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Map tokenised sentences to integer ids, building the vocab as
    needed (reference: rnn/io.py encode_sentences)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    raise MXNetError("word %s not in provided vocab" % word)
                if idx == invalid_label:
                    idx += 1
                vocab[word] = idx
                idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Iterate encoded sentences in length buckets.

    Labels are the data shifted one step left (next-token prediction),
    padded with ``invalid_label`` — the PTB language-model contract.
    The last partial batch of each bucket is padded (``pad`` counts the
    rows), never dropped.
    """

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32", layout="NT"):
        super().__init__(batch_size=batch_size)
        if not buckets:
            lens = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size and i > 0]
        buckets = sorted(buckets)
        if not buckets:
            raise MXNetError("no usable buckets for the given sentences")

        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.layout = layout
        self.invalid_label = invalid_label
        self.buckets = buckets
        self.default_bucket_key = max(buckets)

        from ..bucketing.record import BucketingStats
        self.bucketing = BucketingStats(name="BucketSentenceIter")
        self._warned_tail_pad = False

        # place each sentence in the smallest bucket that fits
        self.data = [[] for _ in buckets]
        lengths = [[] for _ in buckets]
        ndiscard = 0
        for sent in sentences:
            pos = np.searchsorted(buckets, len(sent))
            if pos >= len(buckets):
                ndiscard += 1
                continue
            pad = np.full((buckets[pos],), invalid_label, dtype=dtype)
            pad[:len(sent)] = sent
            self.data[pos].append(pad)
            lengths[pos].append(len(sent))
        # keep 2-D shape even for buckets no sentence landed in
        self.data = [np.asarray(x, dtype=dtype) if x else
                     np.zeros((0, buckets[i]), dtype=dtype)
                     for i, x in enumerate(self.data)]
        self._lengths = [np.asarray(x, np.int64) for x in lengths]
        if ndiscard:
            import logging
            logging.warning("BucketSentenceIter discarded %d sentences "
                            "longer than the largest bucket", ndiscard)
            self.bucketing.note_discard(ndiscard)

        self.batch_axis = layout.find("N")
        shape = (batch_size, self.default_bucket_key) \
            if self.batch_axis == 0 else (self.default_bucket_key,
                                          batch_size)
        self.provide_data = [DataDesc(data_name, shape, layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, layout=layout)]
        # batch index ranges cover the PADDED row count — the final
        # partial batch of each bucket is padded, not dropped (the
        # reference's range(0, n - batch_size + 1, ...) lost up to
        # batch_size - 1 sentences per bucket per epoch)
        self.idx = []
        for i, buck in enumerate(self.data):
            n = len(buck)
            padded_rows = ((n + batch_size - 1) // batch_size) \
                * batch_size
            self.idx.extend((i, j) for j in
                            range(0, padded_rows, batch_size))
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        np.random.shuffle(self.idx)
        # shuffle rows and their true lengths TOGETHER (lengths feed
        # the padding accounting in the bucketing telemetry record)
        for i, buck in enumerate(self.data):
            if len(buck) > 1:
                perm = np.random.permutation(len(buck))
                self.data[i] = buck[perm]
                self._lengths[i] = self._lengths[i][perm]
        # labels: next token; last position gets invalid_label
        self.nddata = []
        self.ndlabel = []
        bs = self.batch_size
        from ..bucketing.padding import pad_along
        for buck in self.data:
            n = len(buck)
            pad_rows = (-n) % bs
            if pad_rows:
                buck = pad_along(buck, n + pad_rows, axis=0,
                                 pad_value=self.invalid_label)
            label = np.full_like(buck, self.invalid_label)
            if buck.shape[1] > 1:
                label[:, :-1] = buck[:, 1:]
            self.nddata.append(_nd_array(buck, dtype=self.dtype))
            self.ndlabel.append(_nd_array(label, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            # epoch end: push the cumulative pad/discard counts to the
            # active telemetry run (no-op without one)
            self.bucketing.emit()
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        bs = self.batch_size
        if self.batch_axis == 0:
            data = self.nddata[i][j:j + bs]
            label = self.ndlabel[i][j:j + bs]
        else:
            data = self.nddata[i][j:j + bs].T
            label = self.ndlabel[i][j:j + bs].T
        L = self.buckets[i]
        n_rows = len(self.data[i])
        pad = max(0, j + bs - n_rows)
        if pad and not self._warned_tail_pad:
            # behavior change vs the reference: tails are padded, not
            # dropped — tell the operator ONCE which contract makes
            # the pad rows numerically inert
            self._warned_tail_pad = True
            import logging
            logging.info(
                "BucketSentenceIter: final partial batches are padded "
                "with invalid_label=%r instead of dropped; use "
                "ignore_label on the loss head (e.g. SoftmaxOutput("
                "use_ignore=True)) and metrics so pad rows — like the "
                "iterator's in-sentence padding — contribute nothing",
                self.invalid_label)
        valid_tokens = int(self._lengths[i][j:j + bs].sum())
        self.bucketing.note_batch(L, bs - pad, bs,
                                  valid_elements=valid_tokens,
                                  total_elements=bs * L)
        shape = (bs, L) if self.batch_axis == 0 else (L, bs)
        return DataBatch(
            [data], [label], pad=pad, bucket_key=L,
            provide_data=[DataDesc(self.data_name, shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, shape,
                                    layout=self.layout)])
