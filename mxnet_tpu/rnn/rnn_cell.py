"""Symbolic RNN cells (reference: python/mxnet/rnn/rnn_cell.py).

Fresh TPU-first structure: every cell is a step function over Symbols;
``unroll`` lays the steps out explicitly (bucketing bounds the number
of distinct compiled programs, exactly the reference's strategy), and
``FusedRNNCell`` lowers the whole sequence to the fused ``RNN``
operator — on TPU that is one ``lax.scan`` in the compiled program, the
analogue of the reference's cuDNN fused kernel (src/operator/rnn-inl.h:380).

Parameter names follow the reference convention
(``<prefix>i2h_weight`` etc.) so exported checkpoints interoperate.
"""
from __future__ import annotations

from ..base import MXNetError
from ..symbol import symbol as _symbol
from .. import symbol as sym

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ResidualCell", "ZoneoutCell"]


class RNNParams:
    """Lazily-created shared variables scoped by a prefix (reference:
    rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._vars = {}

    def get(self, name, **kwargs):
        full = self._prefix + name
        if full not in self._vars:
            self._vars[full] = _symbol.var(full, **kwargs)
        return self._vars[full]


def _zeros_like_state(x, num_hidden):
    """A (batch, num_hidden) zero Symbol derived from a step input
    ``x`` of shape (batch, feature) — no static batch size needed; XLA
    constant-folds it to a zero buffer."""
    col = sym.slice_axis(x, axis=1, begin=0, end=1) * 0.0
    return sym.tile(col, reps=(1, num_hidden))


def _first_step_input(inputs, length, layout):
    axis = layout.find("T")
    if isinstance(inputs, (list, tuple)):
        return inputs[0]
    flat = sym.slice_axis(inputs, axis=axis, begin=0, end=1)
    return sym.Reshape(flat, shape=(0, -1)) if axis == 1 else \
        sym.Reshape(flat, shape=(-3, -1))


class BaseRNNCell:
    """Abstract cell: a step function plus unrolling machinery."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def prefix(self):
        return self._prefix

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    # -- to implement per cell -------------------------------------------
    @property
    def state_info(self):
        """[{'shape': (0, H), '__layout__': 'NC'}, ...] per state."""
        raise NotImplementedError

    def __call__(self, inputs, states):
        """One step: (output, new_states)."""
        raise NotImplementedError

    # -- shared machinery -------------------------------------------------
    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, x=None, **kwargs):
        """Initial states. With ``x`` (a step input Symbol) states are
        zeros derived in-graph — no batch size needed. Otherwise
        ``func`` (e.g. ``mx.sym.zeros``) builds them from
        ``state_info`` shapes with ``batch_size`` substituted."""
        if self._modified:
            raise MXNetError(
                "After applying a modifier cell (e.g. Dropout/Zoneout), "
                "call begin_state on the base cell instead")
        self._init_counter += 1
        states = []
        for i, info in enumerate(self.state_info):
            if x is not None:
                states.append(_zeros_like_state(x, info["shape"][-1]))
                continue
            if func is None:
                raise MXNetError(
                    "begin_state needs either x= (derive zeros in-graph) "
                    "or func= with a concrete batch_size")
            shape = tuple(info["shape"])
            bs = kwargs.get("batch_size")
            if bs:
                # the batch axis is where __layout__ says N is (LNC for
                # fused cells, NC for step cells)
                n_axis = info.get("__layout__", "NC").find("N")
                if 0 <= n_axis < len(shape) and shape[n_axis] == 0:
                    shape = shape[:n_axis] + (bs,) + shape[n_axis + 1:]
            states.append(func(
                name="%sbegin_state_%d_%d" % (self._prefix,
                                              self._init_counter, i),
                shape=shape))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell ``length`` steps.

        inputs: Symbol (layout NTC/TNC) or list of per-step Symbols.
        Returns (outputs, final_states); outputs merged to one Symbol
        on the layout's time axis when ``merge_outputs`` is truthy (or
        None with Symbol input), else a list.
        """
        self.reset()
        step_inputs, merge_default = _to_steps(inputs, length, layout)
        if merge_outputs is None:
            merge_outputs = merge_default
        if begin_state is None:
            states = self.begin_state(x=step_inputs[0])
        else:
            states = list(begin_state)
        outputs = []
        for t in range(length):
            out, states = self(step_inputs[t], states)
            outputs.append(out)
        if merge_outputs:
            outputs = _merge_steps(outputs, layout)
        return outputs, states


def _to_steps(inputs, length, layout):
    """Normalize inputs to a list of (batch, feature) step Symbols."""
    if isinstance(inputs, (list, tuple)):
        if len(inputs) != length:
            raise MXNetError("unroll got %d inputs for length %d"
                             % (len(inputs), length))
        return list(inputs), False
    t_axis = layout.find("T")
    if t_axis not in (0, 1):
        raise MXNetError("unsupported RNN layout %s" % layout)
    if length == 1:
        one = sym.slice_axis(inputs, axis=t_axis, begin=0, end=1)
        # drop the singleton time axis: merge it into the batch dim for
        # TNC (axis 0), keep the batch dim for NTC (axis 1)
        shape = (-3, -1) if t_axis == 0 else (0, -1)
        return [sym.Reshape(one, shape=shape)], True
    steps = sym.split(inputs, num_outputs=length, axis=t_axis,
                      squeeze_axis=True)
    return [steps[i] for i in range(length)], True


def _merge_steps(outputs, layout):
    t_axis = layout.find("T")
    return sym.stack(*outputs, axis=t_axis)


# ---------------------------------------------------------------------------
# concrete cells
# ---------------------------------------------------------------------------

class RNNCell(BaseRNNCell):
    """Elman cell: h' = act(x W_i2h + b + h W_h2h + b)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(states[0], weight=self._hW, bias=self._hB,
                                 num_hidden=self._num_hidden,
                                 name="%sh2h" % name)
        out = sym.Activation(i2h + h2h, act_type=self._activation,
                             name="%sout" % name)
        return out, [out]


class LSTMCell(BaseRNNCell):
    """LSTM cell; gate order (in, forget, cell, out) matches the
    reference so parameters interoperate."""

    def __init__(self, num_hidden, forget_bias=1.0, prefix="lstm_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._forget_bias = forget_bias
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        H = self._num_hidden
        i2h = sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=4 * H, name="%si2h" % name)
        h2h = sym.FullyConnected(states[0], weight=self._hW, bias=self._hB,
                                 num_hidden=4 * H, name="%sh2h" % name)
        gates = i2h + h2h
        g = sym.SliceChannel(gates, num_outputs=4, axis=1,
                             name="%sslice" % name)
        in_gate = sym.sigmoid(g[0])
        forget_gate = sym.sigmoid(g[1] + self._forget_bias)
        in_trans = sym.tanh(g[2])
        out_gate = sym.sigmoid(g[3])
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * sym.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell; gate order (reset, update, new) matches the reference."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        H = self._num_hidden
        i2h = sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=3 * H, name="%si2h" % name)
        h2h = sym.FullyConnected(states[0], weight=self._hW, bias=self._hB,
                                 num_hidden=3 * H, name="%sh2h" % name)
        ir, iz, io = (x for x in sym.SliceChannel(
            i2h, num_outputs=3, axis=1, name="%si2h_slice" % name))
        hr, hz, ho = (x for x in sym.SliceChannel(
            h2h, num_outputs=3, axis=1, name="%sh2h_slice" % name))
        reset = sym.sigmoid(ir + hr)
        update = sym.sigmoid(iz + hz)
        new = sym.tanh(io + reset * ho)
        next_h = update * states[0] + (1.0 - update) * new
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-sequence fused RNN backed by the ``RNN`` operator — one
    ``lax.scan`` on TPU (the analogue of the reference's cuDNN path,
    rnn_cell.py FusedRNNCell / cudnn_rnn-inl.h)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._param = self.params.get("parameters")

    @property
    def state_info(self):
        L = self._num_layers * (2 if self._bidirectional else 1)
        infos = [{"shape": (L, 0, self._num_hidden), "__layout__": "LNC"}]
        if self._mode == "lstm":
            infos.append(dict(infos[0]))
        return infos

    def begin_state(self, func=None, x=None, **kwargs):
        if x is not None:
            # (L, batch, H) zeros derived from a (batch, feature) input
            L = self._num_layers * (2 if self._bidirectional else 1)
            flat = _zeros_like_state(x, self._num_hidden)      # (B, H)
            one = sym.expand_dims(flat, axis=0)                # (1, B, H)
            st = sym.tile(one, reps=(L, 1, 1))
            return [st, st] if self._mode == "lstm" else [st]
        return super().begin_state(func=func, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if isinstance(inputs, (list, tuple)):
            inputs = sym.stack(*inputs, axis=layout.find("T"))
        tnc = inputs if layout == "TNC" else sym.SwapAxis(inputs, dim1=0,
                                                          dim2=1)
        if begin_state is None:
            x0 = _first_step_input(inputs, length, layout)
            begin_state = self.begin_state(x=x0)
        rnn_args = dict(state_size=self._num_hidden,
                        num_layers=self._num_layers,
                        bidirectional=self._bidirectional,
                        mode=self._mode, p=self._dropout,
                        state_outputs=True)
        if self._mode == "lstm":
            out = sym.RNN(tnc, self._param, begin_state[0], begin_state[1],
                          name="%srnn" % self._prefix, **rnn_args)
            outputs, states = out[0], [out[1], out[2]]
        else:
            out = sym.RNN(tnc, self._param, begin_state[0],
                          name="%srnn" % self._prefix, **rnn_args)
            outputs, states = out[0], [out[1]]
        if layout == "NTC":
            outputs = sym.SwapAxis(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs = [x for x in sym.SliceChannel(
                outputs, num_outputs=length, axis=layout.find("T"),
                squeeze_axis=True)]
        return outputs, (states if self._get_next_state else [])


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in order each step."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_info(self):
        return [i for c in self._cells for i in c.state_info]

    def begin_state(self, func=None, x=None, **kwargs):
        states = []
        for c in self._cells:
            states.extend(c.begin_state(func=func, x=x, **kwargs))
        return states

    def _split_states(self, states):
        out = []
        pos = 0
        for c in self._cells:
            n = len(c.state_info)
            out.append(states[pos:pos + n])
            pos += n
        return out

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        for c, s in zip(self._cells, self._split_states(states)):
            inputs, ns = c(inputs, s)
            next_states.extend(ns)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Layer-major unrolling: each cell consumes the full sequence
        before the next (lets FusedRNNCell members stay fused)."""
        self.reset()
        num = len(self._cells)
        begin = self._split_states(begin_state) if begin_state else \
            [None] * num
        states = []
        for i, c in enumerate(self._cells):
            merge = merge_outputs if i == num - 1 else True
            inputs, s = c.unroll(length, inputs, begin_state=begin[i],
                                 layout=layout, merge_outputs=merge)
            states.extend(s)
        return inputs, states


class BidirectionalCell(BaseRNNCell):
    """Run one cell forward and one backward over the sequence and
    concatenate the step outputs on the feature axis."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._l = l_cell
        self._r = r_cell
        self._output_prefix = output_prefix

    @property
    def state_info(self):
        return self._l.state_info + self._r.state_info

    def begin_state(self, func=None, x=None, **kwargs):
        return self._l.begin_state(func=func, x=x, **kwargs) + \
            self._r.begin_state(func=func, x=x, **kwargs)

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot step; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        steps, merge_default = _to_steps(inputs, length, layout)
        if merge_outputs is None:
            merge_outputs = merge_default
        nl = len(self._l.state_info)
        bl = begin_state[:nl] if begin_state else None
        br = begin_state[nl:] if begin_state else None
        l_out, l_states = self._l.unroll(length, steps, begin_state=bl,
                                         layout=layout, merge_outputs=False)
        r_out, r_states = self._r.unroll(length, list(reversed(steps)),
                                         begin_state=br, layout=layout,
                                         merge_outputs=False)
        outs = [sym.Concat(lo, ro, dim=1,
                           name="%st%d" % (self._output_prefix, t))
                for t, (lo, ro) in enumerate(zip(l_out,
                                                 reversed(r_out)))]
        if merge_outputs:
            outs = _merge_steps(outs, layout)
        return outs, l_states + r_states


class ModifierCell(BaseRNNCell):
    """Wraps a cell, delegating params/states (reference: ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__(prefix=base_cell._prefix + "mod_", params=None)
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, x=None, **kwargs):
        self.base_cell._modified = False
        states = self.base_cell.begin_state(func=func, x=x, **kwargs)
        self.base_cell._modified = True
        return states


class DropoutCell(BaseRNNCell):
    """Dropout on the step output (a cell so it can sit in stacks)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._dropout = dropout

    @property
    def state_info(self):
        return []

    def begin_state(self, func=None, x=None, **kwargs):
        return []

    def __call__(self, inputs, states):
        self._counter += 1
        if self._dropout > 0:
            inputs = sym.Dropout(inputs, p=self._dropout,
                                 name="%st%d" % (self._prefix,
                                                 self._counter))
        return inputs, states


class ResidualCell(ModifierCell):
    """Adds the step input to the base cell's output."""

    def __call__(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states


class ZoneoutCell(ModifierCell):
    """Zoneout: randomly keep previous states (reference: ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)

        def mix(p, new, old):
            if p == 0.0:
                return new
            if old is None:     # first step zones out against zeros
                old = sym.zeros_like(new)
            mask = sym.Dropout(sym.ones_like(new), p=p)
            return sym.where(mask, new, old)

        out_mixed = mix(self._zo, out, self._prev_output)
        self._prev_output = out_mixed       # carry the mixed output
        next_states = [mix(self._zs, n, o)
                       for n, o in zip(next_states, states)]
        return out_mixed, next_states
