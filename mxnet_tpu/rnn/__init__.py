"""Symbolic RNN package (reference: python/mxnet/rnn/).

Cells compose Symbols for use with the Module API — most importantly
``BucketingModule`` for variable-length sequence training (BASELINE
config 3: LSTM on PTB). The Gluon-side cells live in
``mxnet_tpu.gluon.rnn``; this package is their symbolic twin with the
reference's parameter naming so checkpoints interoperate.
"""
from .rnn_cell import (RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, BidirectionalCell,
                       DropoutCell, ModifierCell, ResidualCell,
                       ZoneoutCell)
from .io import BucketSentenceIter, encode_sentences
