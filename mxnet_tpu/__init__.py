"""mxnet_tpu — a TPU-native deep learning framework.

A ground-up re-design of Apache MXNet 1.5's capability surface
(reference: loochao/incubator-mxnet) for TPU hardware: JAX/XLA is the
compute substrate, whole graphs lower to single XLA computations,
parallelism is expressed as shardings over a device mesh, and
collectives ride ICI — see SURVEY.md §7 for the architecture
translation table.

Typical use mirrors MXNet:

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu())
    net = mx.gluon.nn.Dense(10)
"""
__version__ = "0.1.0"


def _join_launcher_process_group():
    """Join the process group described by the launcher's DMLC_* env
    contract (tools/launch.py) BEFORE anything touches the jax backend
    — jax.distributed.initialize must run ahead of backend init, and
    importing the package is the first thing every worker does. The
    join itself (env parsing, coordinator retry) lives in
    fault.join_process_group, shared with kvstore creation."""
    import os
    if int(os.environ.get("DMLC_NUM_WORKER", "1") or 1) <= 1 \
            or "DMLC_WORKER_ID" not in os.environ:
        return
    from . import fault
    fault.join_process_group()


_join_launcher_process_group()

from .base import MXNetError
from . import fault
from .fault import CollectiveTimeoutError, InjectedFault
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, \
    num_gpus, num_tpus, gpu_memory_info
from .name import NameManager
from .attribute import AttrScope
from . import base
from . import ops
from . import operator      # registers the `Custom` op before stub codegen
from . import ndarray
from . import ndarray as nd
from . import random
from . import autograd
from . import engine
from . import util
from . import runtime

from .ndarray import NDArray

from . import symbol
from . import symbol as sym
from .symbol import Symbol
from .executor import Executor

from . import initializer
from . import initializer as init
from . import optimizer
from .optimizer import Optimizer
from . import amp
from . import lr_scheduler
from . import metric
from . import kvstore as kvstore_module
from .kvstore import KVStore

from . import io
from . import recordio
from . import rtc
from . import deploy
from . import bucketing
from . import serving
from . import registry
from . import log
from . import libinfo
from . import kvstore_server
from . import callback
from . import monitor
from . import visualization
from . import visualization as viz
from . import profiler
from . import tracing
from . import telemetry
from . import compile_watch
from . import livemetrics
from . import flightrec
from . import checkpoint
from . import model
from . import rnn
from . import storage
from . import contrib
from .model import save_checkpoint, load_checkpoint
from . import module
from . import module as mod
from .module import Module
from . import image
from . import gluon
from . import parallel

from . import test_utils


def kvstore_create(name="local"):
    from .kvstore import create as _create
    return _create(name)


# `mx.kv` style alias used by some reference scripts
kv = kvstore_module
