"""Evaluation metrics (parity: python/mxnet/metric.py, 1,649 LoC).

Metrics run on host numpy — they sit outside the compiled step, like the
reference's CPU-side metric updates (SURVEY §3.1 call stack).
"""
from __future__ import annotations

import math
from collections import OrderedDict

import numpy

from .base import Registry, MXNetError, numeric_types

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "register"]

_REG: Registry = Registry("metric", case_sensitive=False)


def register(klass):
    _REG.register(klass.__name__)(klass)
    return klass


def _as_numpy(x):
    from .ndarray import NDArray
    if isinstance(x, NDArray):
        return x.asnumpy()
    return numpy.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError("Shape of labels {} does not match shape of "
                         "predictions {}".format(label_shape, pred_shape))
    if wrap:
        from .ndarray import NDArray
        if isinstance(labels, NDArray):
            labels = [labels]
        if isinstance(preds, NDArray):
            preds = [preds]
    return labels, preds


class EvalMetric:
    """Base metric (reference: metric.py:56)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = self._kwargs.copy()
        config.update({
            'metric': self.__class__.__name__,
            'name': self.name,
            'output_names': self.output_names,
            'label_names': self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name='composite', output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError("Metric index {} is out of range 0 and {}"
                              .format(index, len(self.metrics)))

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if not isinstance(name, list):
                name = [name]
            if not isinstance(value, list):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)


@register
class Accuracy(EvalMetric):
    """Classification accuracy (reference: metric.py:365)."""

    def __init__(self, axis=1, name='accuracy', output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred_label in zip(labels, preds):
            label = _as_numpy(label)
            pred_label = _as_numpy(pred_label)
            if pred_label.shape != label.shape:
                pred_label = pred_label.argmax(axis=self.axis)
            pred_label = pred_label.astype('int32').reshape(-1)
            label = label.astype('int32').reshape(-1)
            check_label_shapes(label, pred_label)
            self.sum_metric += (pred_label == label).sum()
            self.num_inst += len(pred_label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name='top_k_accuracy', output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        assert self.top_k > 1, 'Please use Accuracy if top_k is no more than 1'
        self.name += '_%d' % self.top_k

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred_label in zip(labels, preds):
            assert len(pred_label.shape) <= 2, \
                'Predictions should be no more than 2 dims'
            pred = _as_numpy(pred_label).astype('float32')
            pred_label = numpy.argpartition(pred, -self.top_k)
            label = _as_numpy(label).astype('int32')
            check_label_shapes(label, pred_label)
            num_samples = pred_label.shape[0]
            num_dims = len(pred_label.shape)
            if num_dims == 1:
                self.sum_metric += (pred_label.flat == label.flat).sum()
            elif num_dims == 2:
                num_classes = pred_label.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    self.sum_metric += (
                        pred_label[:, num_classes - 1 - j].flat
                        == label.flat).sum()
            self.num_inst += num_samples


class _BinaryClassificationMetrics:
    def __init__(self):
        self.reset_stats()

    def update_binary_stats(self, label, pred):
        pred = _as_numpy(pred)
        label = _as_numpy(label).astype('int32')
        pred_label = numpy.argmax(pred, axis=1)
        check_label_shapes(label, pred)
        if len(numpy.unique(label)) > 2:
            raise ValueError("%s currently only supports binary "
                             "classification." % self.__class__.__name__)
        pred_true = (pred_label == 1)
        pred_false = 1 - pred_true
        label_true = (label == 1)
        label_false = 1 - label_true
        self.true_positives += (pred_true * label_true).sum()
        self.false_positives += (pred_true * label_false).sum()
        self.false_negatives += (pred_false * label_true).sum()
        self.true_negatives += (pred_false * label_false).sum()

    @property
    def precision(self):
        if self.true_positives + self.false_positives > 0:
            return float(self.true_positives) / (
                self.true_positives + self.false_positives)
        return 0.

    @property
    def recall(self):
        if self.true_positives + self.false_negatives > 0:
            return float(self.true_positives) / (
                self.true_positives + self.false_negatives)
        return 0.

    @property
    def fscore(self):
        if self.precision + self.recall > 0:
            return 2 * self.precision * self.recall / (
                self.precision + self.recall)
        return 0.

    @property
    def matthewscc(self):
        if not self.total_examples:
            return 0.
        true_pos = float(self.true_positives)
        false_pos = float(self.false_positives)
        false_neg = float(self.false_negatives)
        true_neg = float(self.true_negatives)
        terms = [(true_pos + false_pos), (true_pos + false_neg),
                 (true_neg + false_pos), (true_neg + false_neg)]
        denom = 1.
        for t in filter(lambda t: t != 0., terms):
            denom *= t
        return ((true_pos * true_neg) - (false_pos * false_neg)) \
            / math.sqrt(denom)

    @property
    def total_examples(self):
        return self.false_negatives + self.false_positives + \
            self.true_negatives + self.true_positives

    def reset_stats(self):
        self.false_positives = 0
        self.false_negatives = 0
        self.true_positives = 0
        self.true_negatives = 0


@register
class F1(EvalMetric):
    def __init__(self, name='f1', output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self.metrics = _BinaryClassificationMetrics()
        EvalMetric.__init__(self, name=name, output_names=output_names,
                            label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(label, pred)
        if self.average == "macro":
            self.sum_metric += self.metrics.fscore
            self.num_inst += 1
            self.metrics.reset_stats()
        else:
            self.sum_metric = self.metrics.fscore * \
                self.metrics.total_examples
            self.num_inst = self.metrics.total_examples

    def reset(self):
        self.sum_metric = 0.
        self.num_inst = 0.
        if hasattr(self, 'metrics'):
            self.metrics.reset_stats()


@register
class MCC(EvalMetric):
    def __init__(self, name='mcc', output_names=None, label_names=None,
                 average="macro"):
        self._average = average
        self._metrics = _BinaryClassificationMetrics()
        EvalMetric.__init__(self, name=name, output_names=output_names,
                            label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self._metrics.update_binary_stats(label, pred)
        if self._average == "macro":
            self.sum_metric += self._metrics.matthewscc
            self.num_inst += 1
            self._metrics.reset_stats()
        else:
            self.sum_metric = self._metrics.matthewscc * \
                self._metrics.total_examples
            self.num_inst = self._metrics.total_examples

    def reset(self):
        self.sum_metric = 0.
        self.num_inst = 0.
        if hasattr(self, '_metrics'):
            self._metrics.reset_stats()


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name='perplexity',
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            assert label.size == pred.size / pred.shape[-1], \
                "shape mismatch"
            label = label.reshape((label.size,)).astype('int32')
            probs = pred.reshape(-1, pred.shape[-1])[
                numpy.arange(label.size), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label).astype(probs.dtype)
                num -= numpy.sum(ignore)
                probs = probs * (1 - ignore) + ignore
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, probs)))
            num += label.size
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float('nan'))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    def __init__(self, name='mae', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += numpy.abs(label - pred).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name='mse', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


@register
class RMSE(EvalMetric):
    def __init__(self, name='rmse', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += numpy.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name='cross-entropy', output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(EvalMetric):
    def __init__(self, eps=1e-12, name='nll-loss', output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            label = label.ravel()
            num_examples = pred.shape[0]
            assert label.shape[0] == num_examples, \
                (label.shape[0], num_examples)
            prob = pred[numpy.arange(num_examples, dtype=numpy.int64),
                        numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += num_examples


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name='pearsonr', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            check_label_shapes(label, pred, False, True)
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            self.sum_metric += numpy.corrcoef(pred.ravel(),
                                              label.ravel())[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of raw loss outputs (reference: metric.py Loss)."""

    def __init__(self, name='loss', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        from .ndarray import NDArray
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            loss = _as_numpy(pred).sum()
            self.sum_metric += loss
            self.num_inst += pred.size


@register
class Torch(Loss):
    def __init__(self, name='torch', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name='caffe', output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find('<') != -1:
                name = 'custom(%s)' % name
        super().__init__(name, output_names, label_names, feval=feval,
                         allow_extra_outputs=allow_extra_outputs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for pred, label in zip(preds, labels):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, CompositeEvalMetric):
        return metric
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite_metric = CompositeEvalMetric()
        for child_metric in metric:
            composite_metric.add(create(child_metric, *args, **kwargs))
        return composite_metric
    if isinstance(metric, str):
        cls = _REG.find(metric)
        if cls is None:
            # convenience aliases
            aliases = {"acc": Accuracy, "ce": CrossEntropy,
                       "nll_loss": NegativeLogLikelihood,
                       "top_k_acc": TopKAccuracy}
            cls = aliases.get(metric.lower())
        if cls is None:
            raise MXNetError("Metric must be either callable or str; "
                             "unknown: %s" % metric)
        return cls(*args, **kwargs)
    raise TypeError("metric should be either str, callable or EvalMetric")
