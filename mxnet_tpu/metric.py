"""Evaluation metrics (API parity: python/mxnet/metric.py, 1,649 LoC).

Own architecture: every built-in metric is a *batch statistic* — a
method returning ``(stat_sum, count)`` for one (label, pred) pair — and
the shared base accumulates those into the running ``sum_metric /
num_inst`` average. Regression metrics share one elementwise-error
class, the F1/MCC pair share one confusion-matrix accumulator built on
``numpy.bincount``, and likelihood metrics share one gather-probs core.
Metrics run on host numpy, outside the compiled step, exactly where the
reference runs them (SURVEY §3.1 call stack).
"""
from __future__ import annotations

import math
from collections import OrderedDict

import numpy

from .base import Registry, MXNetError, numeric_types

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "register"]

_REG: Registry = Registry("metric", case_sensitive=False)


def register(klass):
    _REG.register(klass.__name__)(klass)
    return klass


def _host(x):
    """Fetch to host numpy (NDArray or array-like)."""
    asnumpy = getattr(x, "asnumpy", None)
    return asnumpy() if asnumpy is not None else numpy.asarray(x)


def _listify(x):
    from .ndarray import NDArray
    return [x] if isinstance(x, NDArray) else x


def check_label_shapes(labels, preds, wrap=False, shape=False):
    """Reference-compatible shape guard (metric.py:32)."""
    got = (labels.shape, preds.shape) if shape else \
        (len(labels), len(preds))
    if got[0] != got[1]:
        raise ValueError(
            "Shape of labels {} does not match shape of predictions {}"
            .format(*got))
    if wrap:
        labels, preds = _listify(labels), _listify(preds)
    return labels, preds


def _as_2d(a):
    return a.reshape(a.shape[0], 1) if a.ndim == 1 else a


def _gathered_probs(label, pred):
    """Probability assigned to each sample's true class: pred rows
    indexed by the integer labels."""
    flat = label.ravel().astype(numpy.int64)
    rows = pred.reshape(-1, pred.shape[-1])
    if flat.shape[0] != rows.shape[0]:
        raise ValueError(
            "label count %d does not match prediction rows %d"
            % (flat.shape[0], rows.shape[0]))
    return flat, rows[numpy.arange(flat.shape[0]), flat]


class EvalMetric:
    """Running-average metric base (reference: metric.py:56).

    Built-ins implement :meth:`_batch_stat`; overriding :meth:`update`
    wholesale (the reference's protocol) also works.
    """

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names, self.label_names = output_names, label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        cfg = dict(self._kwargs,
                   metric=type(self).__name__, name=self.name,
                   output_names=self.output_names,
                   label_names=self.label_names)
        return cfg

    # -- accumulation -----------------------------------------------------
    def _batch_stat(self, label, pred):
        raise NotImplementedError(
            "%s defines neither _batch_stat nor update" % type(self))

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            s, n = self._batch_stat(_host(label), _host(pred))
            self.sum_metric += s
            self.num_inst += n

    def update_dict(self, label, pred):
        pick = lambda d, names: [d[k] for k in names] if names is not None \
            else list(d.values())
        self.update(pick(label, self.label_names),
                    pick(pred, self.output_names))

    def reset(self):
        self.sum_metric, self.num_inst = 0.0, 0

    # -- readout ----------------------------------------------------------
    def _value(self):
        return self.sum_metric / self.num_inst

    def get(self):
        if not self.num_inst:
            return (self.name, float("nan"))
        return (self.name, self._value())

    def get_name_value(self):
        name, value = self.get()
        names = name if isinstance(name, list) else [name]
        values = value if isinstance(value, list) else [value]
        return list(zip(names, values))


@register
class CompositeEvalMetric(EvalMetric):
    """Fan-out wrapper over child metrics (reference: metric.py:212)."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError(
                "Metric index {} is out of range 0 and {}"
                .format(index, len(self.metrics)))

    def update_dict(self, labels, preds):
        for child in self.metrics:
            child.update_dict(labels, preds)

    def update(self, labels, preds):
        for child in self.metrics:
            child.update(labels, preds)

    def reset(self):
        for child in getattr(self, "metrics", ()):
            child.reset()

    def get(self):
        names, values = [], []
        for child in self.metrics:
            for n, v in child.get_name_value():
                names.append(n)
                values.append(v)
        return (names, values)


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

@register
class Accuracy(EvalMetric):
    """Fraction of argmax predictions equal to the label
    (reference: metric.py:365).

    ``ignore_label`` drops positions whose label equals it BEFORE
    counting — hits and the denominator alike — so padded bucketed
    batches (``mxnet_tpu.bucketing``) score identically to their
    unpadded samples: the selection is an ordered boolean take, the
    ignored rows simply never existed."""

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None, ignore_label=None):
        super().__init__(name, output_names, label_names, axis=axis,
                         ignore_label=ignore_label)
        self.axis = axis
        self.ignore_label = ignore_label

    def _batch_stat(self, label, pred):
        if pred.shape != label.shape:
            pred = pred.argmax(axis=self.axis)
        pred = pred.ravel().astype(numpy.int32)
        label_raw = label.ravel()
        label = label_raw.astype(numpy.int32)
        check_label_shapes(label, pred)     # no silent broadcasting
        if self.ignore_label is not None:
            keep = label_raw != self.ignore_label
            pred, label = pred[keep], label[keep]
        hits = numpy.equal(pred, label)
        return hits.sum(), hits.size


@register
class TopKAccuracy(EvalMetric):
    """Label within the k highest-scored classes
    (reference: metric.py:439)."""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        if top_k <= 1:
            raise ValueError("use Accuracy for top_k <= 1")
        super().__init__("%s_%d" % (name, top_k), output_names,
                         label_names, top_k=top_k)
        self.top_k = top_k

    def _batch_stat(self, label, pred):
        if pred.ndim > 2:
            raise ValueError("TopKAccuracy expects <= 2-d predictions")
        label = label.astype(numpy.int64).ravel()
        if pred.ndim == 1:
            return numpy.equal(pred.astype(numpy.int64),
                               label).sum(), label.shape[0]
        k = min(self.top_k, pred.shape[1])
        top = numpy.argpartition(pred.astype(numpy.float32), -k)[:, -k:]
        hits = (top == label[:, None]).any(axis=1)
        return hits.sum(), label.shape[0]


class _Confusion:
    """2x2 confusion counts via one bincount per batch."""

    __slots__ = ("counts",)

    def __init__(self):
        self.clear()

    def clear(self):
        self.counts = numpy.zeros(4, dtype=numpy.int64)

    def absorb(self, label, pred_scores):
        label = label.astype(numpy.int64).ravel()
        if numpy.unique(label).size > 2:
            raise ValueError(
                "confusion-matrix metrics support binary labels only")
        check_label_shapes(label, pred_scores)
        # anything other than class 1 counts as negative — matches the
        # reference's (pred_label == 1)/(label == 1) convention, and
        # keeps bincount indices in [0, 4) for signed labels or extra
        # prediction columns
        truth = (label == 1).astype(numpy.int64)
        decided = (pred_scores.argmax(axis=1) == 1).astype(numpy.int64)
        self.counts += numpy.bincount(2 * truth + decided, minlength=4)

    # counts layout: [TN, FP, FN, TP]
    tn = property(lambda self: float(self.counts[0]))
    fp = property(lambda self: float(self.counts[1]))
    fn = property(lambda self: float(self.counts[2]))
    tp = property(lambda self: float(self.counts[3]))

    @property
    def total(self):
        return int(self.counts.sum())

    @property
    def f1(self):
        denom = 2 * self.tp + self.fp + self.fn
        return 2 * self.tp / denom if denom else 0.0

    @property
    def mcc(self):
        num = self.tp * self.tn - self.fp * self.fn
        factors = [self.tp + self.fp, self.tp + self.fn,
                   self.tn + self.fp, self.tn + self.fn]
        denom = 1.0
        for f in factors:
            if f:
                denom *= f
        return num / math.sqrt(denom) if self.total else 0.0


class _ConfusionMetric(EvalMetric):
    """Shared macro/micro averaging over a _Confusion score."""

    _score_of = None        # property name on _Confusion

    def __init__(self, name, output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self._conf = _Confusion()
        super().__init__(name, output_names=output_names,
                         label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            self._conf.absorb(_host(label), _host(pred))
        score = getattr(self._conf, self._score_of)
        if self.average == "macro":
            self.sum_metric += score
            self.num_inst += 1
            self._conf.clear()
        else:
            self.sum_metric = score * self._conf.total
            self.num_inst = self._conf.total

    def reset(self):
        self.sum_metric, self.num_inst = 0.0, 0
        if hasattr(self, "_conf"):
            self._conf.clear()


@register
class F1(_ConfusionMetric):
    """Binary F1 (reference: metric.py:565)."""
    _score_of = "f1"

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names, average)


@register
class MCC(_ConfusionMetric):
    """Matthews correlation coefficient (reference: metric.py:665)."""
    _score_of = "mcc"

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names, average)


# ---------------------------------------------------------------------------
# likelihood family
# ---------------------------------------------------------------------------

@register
class Perplexity(EvalMetric):
    """exp of the mean negative log prob of the true class, with
    optional ignored label id (reference: metric.py:761)."""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label, self.axis = ignore_label, axis

    def _batch_stat(self, label, pred):
        flat, probs = _gathered_probs(label, pred)
        count = flat.shape[0]
        if self.ignore_label is not None:
            # ordered boolean SELECTION, not a where()-to-1.0 mask: the
            # kept probabilities are the identical array an unpadded
            # batch would produce, so the summed NLL (and therefore the
            # perplexity of a padded bucketed batch) matches the
            # unpadded value bit-for-bit — where() would interleave
            # exact zeros and shift numpy's pairwise-sum grouping
            keep = flat != self.ignore_label
            probs = probs[keep]
            count = int(keep.sum())
        nll = -numpy.log(numpy.maximum(probs, 1e-10)).sum()
        return nll, count

    def _value(self):
        return math.exp(self.sum_metric / self.num_inst)


class _GatheredNLL(EvalMetric):
    """Mean -log(p_true + eps); CrossEntropy and NLL differ only in
    their default name (reference: metric.py:846, :917)."""

    def __init__(self, eps, name, output_names, label_names):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def _batch_stat(self, label, pred):
        flat, probs = _gathered_probs(label, pred)
        return -numpy.log(probs + self.eps).sum(), flat.shape[0]


@register
class CrossEntropy(_GatheredNLL):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register
class NegativeLogLikelihood(_GatheredNLL):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


# ---------------------------------------------------------------------------
# regression
# ---------------------------------------------------------------------------

class _ElementwiseError(EvalMetric):
    """Batch-mean of an elementwise error, averaged over batches."""

    @staticmethod
    def _error(diff):
        raise NotImplementedError

    def _batch_stat(self, label, pred):
        diff = _as_2d(label) - _as_2d(pred)
        return self._error(diff), 1


@register
class MAE(_ElementwiseError):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    @staticmethod
    def _error(diff):
        return numpy.abs(diff).mean()


@register
class MSE(_ElementwiseError):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    @staticmethod
    def _error(diff):
        return numpy.square(diff).mean()


@register
class RMSE(_ElementwiseError):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    @staticmethod
    def _error(diff):
        return math.sqrt(numpy.square(diff).mean())


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)

    def _batch_stat(self, label, pred):
        check_label_shapes(label, pred, False, True)
        return numpy.corrcoef(pred.ravel(), label.ravel())[0, 1], 1


# ---------------------------------------------------------------------------
# loss passthrough + custom
# ---------------------------------------------------------------------------

@register
class Loss(EvalMetric):
    """Mean of raw loss outputs; ignores labels
    (reference: metric.py:1421)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in _listify(preds):
            self.sum_metric += float(_host(pred).sum())
            self.num_inst += pred.size


@register
class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wraps feval(label, pred) -> value or (sum, count)
    (reference: metric.py:1480)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if "<" in name:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names, feval=feval,
                         allow_extra_outputs=allow_extra_outputs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for pred, label in zip(preds, labels):
            result = self._feval(_host(label), _host(pred))
            if isinstance(result, tuple):
                s, n = result
            else:
                s, n = result, 1
            self.sum_metric += s
            self.num_inst += n


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Lift a numpy feval into a CustomMetric (reference: metric.py:1566)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


_SHORTHAND = {"acc": "Accuracy", "ce": "CrossEntropy",
              "nll_loss": "NegativeLogLikelihood",
              "top_k_acc": "TopKAccuracy"}


def create(metric, *args, **kwargs):
    """Resolve str / callable / list / instance into an EvalMetric."""
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, list):
        bundle = CompositeEvalMetric()
        for item in metric:
            bundle.add(create(item, *args, **kwargs))
        return bundle
    if isinstance(metric, str):
        key = _SHORTHAND.get(metric.lower(), metric)
        cls = _REG.find(key)
        if cls is not None:
            return cls(*args, **kwargs)
        raise MXNetError(
            "Metric must be either callable or str; unknown: %s" % metric)
    raise TypeError("metric should be either str, callable or EvalMetric")
