"""Device context management.

Parity with ``python/mxnet/context.py`` in the reference, re-targeted at
JAX's device model. A :class:`Context` names a (device_type, device_id)
pair; it resolves lazily to a concrete ``jax.Device``:

- ``mx.cpu(i)``  → the JAX CPU backend device *i* (always available).
- ``mx.tpu(i)``  → TPU device *i* (the native target of this framework).
- ``mx.gpu(i)``  → accepted for API compatibility; resolves to the default
  accelerator if one exists (so reference scripts that say ``mx.gpu()``
  run unmodified on TPU), else raises at resolution time.

Unlike the reference there is no per-context memory pool to manage —
XLA owns HBM — so the context is purely a placement annotation.
"""
from __future__ import annotations

import threading
from typing import Optional

from .base import MXNetError, classproperty

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context",
           "num_gpus", "num_tpus", "gpu_memory_info"]


class Context:
    """Device context (reference: python/mxnet/context.py:29)."""

    # Parity with reference devtype mapping (context.py:58-66) + tpu.
    devtype2str = {1: 'cpu', 2: 'gpu', 3: 'cpu_pinned', 5: 'cpu_shared', 6: 'tpu'}
    devstr2type = {v: k for k, v in devtype2str.items()}

    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in Context.devstr2type:
                raise MXNetError("unknown device type %r" % (device_type,))
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self) -> str:
        return Context.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_typeid == other.device_typeid
                and self.device_id == other.device_id)

    def __str__(self):
        return '%s(%d)' % (self.device_type, self.device_id)

    def __repr__(self):
        return self.__str__()

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = _initial_default_context()
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # ---- JAX resolution ------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device."""
        import jax
        dt = self.device_type
        if dt in ('cpu', 'cpu_pinned', 'cpu_shared'):
            try:
                return jax.devices('cpu')[self.device_id]
            except (RuntimeError, IndexError):
                # Platform-restricted process (e.g. JAX_PLATFORMS=tpu):
                # fall back to default devices.
                return jax.devices()[0]
        # gpu/tpu: use the default backend's devices (on this stack that is
        # the TPU / accelerator backend; 'gpu' accepted for compat).
        devs = jax.devices()
        if devs and devs[0].platform == 'cpu' and dt in ('gpu', 'tpu'):
            # No accelerator present (e.g. CPU-only test runs): place on cpu.
            return devs[self.device_id % len(devs)]
        if self.device_id >= len(devs):
            raise MXNetError(
                "context %s: only %d device(s) available" % (self, len(devs)))
        return devs[self.device_id]

    def empty_cache(self):
        """No-op: XLA owns the memory pool (reference frees GPU pool here)."""

    @classproperty
    def default_ctx(cls):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = _initial_default_context()
        return Context._default_ctx.value


def _initial_default_context() -> "Context":
    """First-use default: the accelerator when one is present, else cpu.

    This framework is TPU-native — a bare ``mx.nd.array(...)`` must land
    on the TPU, exactly as the reference lands on the build's native
    device. ``MXNET_DEFAULT_CONTEXT=cpu`` (or ``tpu``/``gpu``) overrides.
    Unit tests pin ``JAX_PLATFORMS=cpu`` and therefore still get cpu.
    """
    from . import envs
    override = envs.get_str("MXNET_DEFAULT_CONTEXT").lower()
    if override:
        return Context(override, 0)
    try:
        import jax
        if jax.devices()[0].platform != 'cpu':
            return Context('tpu', 0)
    except Exception:  # backend init failure → host arrays still work
        pass
    return Context('cpu', 0)


def cpu(device_id=0):
    """Return a CPU context (reference: context.py:201)."""
    return Context('cpu', device_id)


def cpu_pinned(device_id=0):
    return Context('cpu_pinned', device_id)


def gpu(device_id=0):
    """Accelerator context; on this framework it aliases the TPU backend."""
    return Context('gpu', device_id)


def tpu(device_id=0):
    """TPU context — the native device of this framework."""
    return Context('tpu', device_id)


def num_gpus():
    """Number of accelerator devices visible (reference: context.py:242)."""
    import jax
    devs = jax.devices()
    if devs and devs[0].platform != 'cpu':
        return len(devs)
    return 0


def num_tpus():
    import jax
    try:
        return len([d for d in jax.devices() if d.platform != 'cpu'])
    except RuntimeError:
        return 0


def gpu_memory_info(device_id=0):
    """(free, total) memory on accelerator ``device_id``."""
    import jax
    devs = [d for d in jax.devices() if d.platform != 'cpu']
    if not devs:
        raise MXNetError("no accelerator device present")
    stats = devs[device_id].memory_stats() or {}
    total = stats.get('bytes_limit', 0)
    used = stats.get('bytes_in_use', 0)
    return (total - used, total)


def current_context() -> Context:
    """The thread-local default context (reference: context.py:257)."""
    return Context.default_ctx
