"""Logging helpers (parity: python/mxnet/log.py): a formatter with
level-colored output on TTYs and ``get_logger``/``getLogger``."""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger", "getLogger", "DEBUG", "INFO", "WARNING",
           "ERROR", "CRITICAL", "NOTSET"]

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
CRITICAL = logging.CRITICAL
NOTSET = logging.NOTSET

PY3 = True


class _Formatter(logging.Formatter):
    """Level-aware formatter; colors on TTY streams
    (ref log.py:37)."""

    _COLORS = {logging.WARNING: "\x1b[33m", logging.ERROR: "\x1b[31m",
               logging.CRITICAL: "\x1b[35m", logging.DEBUG: "\x1b[36m"}

    def __init__(self, colored=True):
        self.colored = colored
        super().__init__(datefmt="%m%d %H:%M:%S")

    def _label(self, level):
        if level == logging.WARNING:
            return "W"
        if level == logging.ERROR:
            return "E"
        if level == logging.CRITICAL:
            return "C"
        if level == logging.DEBUG:
            return "D"
        return "I"

    def format(self, record):
        label = self._label(record.levelno)
        fmt = label + "%(asctime)s %(process)d %(pathname)s:" \
            "%(funcName)s:%(lineno)d] %(message)s"
        if self.colored and record.levelno in self._COLORS:
            fmt = self._COLORS[record.levelno] + fmt + "\x1b[0m"
        self._style._fmt = fmt
        return super().format(record)


def getLogger(name=None, filename=None, filemode=None, level=WARNING):
    """(deprecated spelling kept for parity) — see get_logger."""
    return get_logger(name, filename, filemode, level)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """A logger configured with the framework formatter
    (ref log.py:90)."""
    logger = logging.getLogger(name)
    if name is not None and not getattr(logger, "_init_done", False):
        logger._init_done = True
        if filename:
            mode = filemode if filemode else "a"
            hdlr = logging.FileHandler(filename, mode)
            hdlr.setFormatter(_Formatter(colored=False))
        else:
            hdlr = logging.StreamHandler()
            hdlr.setFormatter(_Formatter(
                colored=getattr(sys.stderr, "isatty", lambda: False)()))
        logger.addHandler(hdlr)
    logger.setLevel(level)
    return logger
