"""Attribute scoping for symbols (parity with python/mxnet/attribute.py).

``with mx.AttrScope(ctx_group='dev1'):`` is the reference's manual
model-parallel placement mechanism (SURVEY §2.2). In this framework the
``ctx_group`` attribute is consumed at bind time when ``group2ctx`` is
passed (``Symbol.bind`` / ``Module(group2ctxs=...)``): the executor
partitions the graph into per-group segment programs pinned to each
group's device, with explicit cross-group activation transfer — see
``placement.GroupedProgram`` (ref graph_executor.cc:907 AssignContext).
"""
from __future__ import annotations

import threading

from .base import string_types

__all__ = ["AttrScope"]


class AttrScope:
    _current = threading.local()

    def __init__(self, **kwargs):
        self._old_scope = None
        for value in kwargs.values():
            if not isinstance(value, string_types):
                raise ValueError("Attributes need to be a string")
        self._attr = kwargs

    def get(self, attr):
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        self._old_scope = AttrScope._current.value
        attr = AttrScope._current.value._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_scope
        AttrScope._current.value = self._old_scope

    @staticmethod
    def current():
        if not hasattr(AttrScope._current, "value"):
            AttrScope._current.value = AttrScope()
        return AttrScope._current.value
