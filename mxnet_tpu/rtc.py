"""Runtime kernel compilation — the TPU-native ``mx.rtc``.

Reference surface: ``mx.rtc.CudaModule`` compiles CUDA C source at
runtime via NVRTC and launches kernels on GPU NDArrays
(python/mxnet/rtc.py:42, include/mxnet/rtc.h:39). The TPU-native
translation (SURVEY §7: "RTC ≙ Pallas-from-source") keeps the same
object model — module(source).get_kernel(name, signature).launch(args,
ctx, grid, block) — but the source is PYTHON text defining Pallas
kernel bodies, compiled at runtime with exec + pallas_call:

    source = '''
    def axpy(alpha, x_ref, y_ref):
        y_ref[...] = y_ref[...] + alpha * x_ref[...]
    '''
    mod = PallasModule(source)
    k = mod.get_kernel("axpy", "float alpha, const float *x, float *y")
    k.launch((2.0, x, y), mx.cpu(), (1, 1, 1), (1, 1, 1))

Signature grammar matches the reference exactly: ``const`` marks an
input array, ``*`` marks an array, bare types are scalars. Non-const
arrays are in-out (the kernel reads and writes their ref, backed by
``input_output_aliases``), and launch writes results back into the
passed NDArrays — the reference's mutation contract. ``grid_dims``
maps onto the Pallas grid; ``block_dims`` has no TPU counterpart
(blocking comes from BlockSpecs / ref indexing) and must be (1, 1, 1).
On non-TPU platforms kernels run in Pallas interpret mode.
"""
from __future__ import annotations

import re

import numpy as _np

from .base import MXNetError

__all__ = ["PallasModule", "PallasKernel", "CudaModule"]

# reference rtc.py _DTYPE_CPP_TO_NP, plus numpy-style spellings
_DTYPE_TO_NP = {
    "float": _np.float32, "double": _np.float64, "__half": _np.float16,
    "uint8_t": _np.uint8, "int": _np.int32, "int32_t": _np.int32,
    "int8_t": _np.int8, "char": _np.int8, "int64_t": _np.int64,
    "float32": _np.float32, "float64": _np.float64,
    "float16": _np.float16, "bfloat16": "bfloat16",
    "int32": _np.int32, "int64": _np.int64, "int8": _np.int8,
    "uint8": _np.uint8, "bool": _np.bool_,
}

_SIG_RE = re.compile(
    r"""^\s*(const)?\s*([\w_]+)\s*(\*)?\s*([\w_]+)?\s*$""")


class PallasModule:
    """Compile Python/Pallas source text at runtime."""

    def __init__(self, source, options=(), exports=()):
        del options                      # nvrtc flags: no analogue
        self._source = source
        ns = {}
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        ns.update({"jax": jax, "jnp": jnp, "pl": pl})
        exec(compile(source, "<mx.rtc>", "exec"), ns)
        self._ns = ns
        for name in exports:
            if name not in ns:
                raise MXNetError(
                    "rtc source does not define exported name %r"
                    % name)

    def get_kernel(self, name, signature):
        fn = self._ns.get(name)
        if not callable(fn):
            raise MXNetError(
                "rtc module has no kernel function %r" % name)
        is_ndarray, is_const, dtypes = [], [], []
        for arg in re.sub(r"\s+", " ", signature).split(","):
            m = _SIG_RE.match(arg)
            if not m or m.groups()[1] == "const":
                raise ValueError(
                    'Invalid function prototype "%s". Must be in the '
                    'form of "(const) type (*) (name)"' % arg)
            is_const.append(bool(m.groups()[0]))
            dtype = m.groups()[1]
            is_ndarray.append(bool(m.groups()[2]))
            if dtype not in _DTYPE_TO_NP:
                raise TypeError(
                    "Unsupported kernel argument type %s. Supported: %s"
                    % (arg, ", ".join(sorted(_DTYPE_TO_NP))))
            dtypes.append(_np.dtype(_DTYPE_TO_NP[dtype]))
        return PallasKernel(fn, name, is_ndarray, is_const, dtypes)


class PallasKernel:
    """Launchable kernel; create via ``PallasModule.get_kernel``."""

    def __init__(self, fn, name, is_ndarray, is_const, dtypes):
        self._fn = fn
        self._name = name
        self._is_ndarray = is_ndarray
        self._is_const = is_const
        self._dtypes = dtypes

    def launch(self, args, ctx, grid_dims=(1, 1, 1),
               block_dims=(1, 1, 1), shared_mem=0):
        """Run the kernel. Arrays marked const are inputs; other
        arrays are in-out and receive the results in place (the
        reference CudaKernel.launch contract)."""
        from .ndarray import NDArray
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        if len(grid_dims) != 3 or len(block_dims) != 3:
            raise ValueError(
                "grid_dims/block_dims must be tuples of 3 integers")
        if tuple(block_dims) != (1, 1, 1):
            raise MXNetError(
                "block_dims have no TPU counterpart (blocking comes "
                "from Pallas BlockSpecs); pass (1, 1, 1)")
        if shared_mem:
            raise MXNetError("shared_mem has no TPU counterpart")
        if len(args) != len(self._dtypes):
            raise MXNetError(
                "PallasKernel(%s) expects %d arguments but got %d"
                % (self._name, len(self._dtypes), len(args)))

        grid = tuple(int(g) for g in grid_dims if int(g) > 1)
        in_vals = []          # const array values, in signature order
        out_specs = []        # (signature position, NDArray)
        scalars = {}
        for i, (arg, is_nd, const, dt) in enumerate(
                zip(args, self._is_ndarray, self._is_const,
                    self._dtypes)):
            if is_nd:
                if not isinstance(arg, NDArray):
                    raise MXNetError(
                        "argument %d of %s must be an NDArray"
                        % (i, self._name))
                if const:
                    in_vals.append(arg._data.astype(jnp.dtype(dt)))
                else:
                    out_specs.append((i, arg))
            else:
                # numpy scalar, baked as a compile-time literal (Pallas
                # rejects closure-captured traced values; the reference
                # also passes scalars by value per launch)
                scalars[i] = _np.dtype(dt).type(arg)
        if not out_specs:
            raise MXNetError(
                "kernel %s has no writable (non-const) array argument"
                % self._name)

        n_in = len(in_vals)
        const_pos = [i for i, (nd, c) in enumerate(
            zip(self._is_ndarray, self._is_const)) if nd and c]
        out_pos = [i for i, _ in out_specs]

        def body(*refs):
            # refs: const inputs, aliased in-out inputs, then outputs;
            # rebuild the kernel's signature-ordered argument list,
            # handing the OUTPUT ref for in-out positions
            ins = refs[:n_in]
            outs = refs[n_in + len(out_specs):]
            call_args = []
            for i in range(len(self._dtypes)):
                if i in scalars:
                    call_args.append(scalars[i])
                elif i in out_pos:
                    call_args.append(outs[out_pos.index(i)])
                else:
                    call_args.append(ins[const_pos.index(i)])
            self._fn(*call_args)

        platform = jax.devices()[0].platform \
            if ctx is None else ctx.device_type
        interpret = platform != "tpu"
        out_shapes = [jax.ShapeDtypeStruct(a._data.shape,
                                           jnp.dtype(self._dtypes[i]))
                      for i, a in out_specs]
        io_alias = {n_in + j: j for j in range(len(out_specs))}
        kwargs = {"grid": grid} if grid else {}
        call = pl.pallas_call(
            body, out_shape=out_shapes,
            input_output_aliases=io_alias, interpret=interpret,
            **kwargs)
        results = call(*in_vals,
                       *[a._data.astype(jnp.dtype(self._dtypes[i]))
                         for i, a in out_specs])
        if not isinstance(results, (tuple, list)):
            results = (results,)
        for (i, arr), val in zip(out_specs, results):
            arr._set_data(val.astype(arr._data.dtype))


# the reference's class name kept as an alias so ported scripts run
CudaModule = PallasModule
