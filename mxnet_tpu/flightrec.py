"""Flight recorder: rate-limited post-mortem bundles for fleet
incidents — the "what was true at the moment it fired" the live
observability stack (tracing ring, /metrics, SLO watchdog) cannot
answer after the fact.

The live stack is a window: the trace ring rotates, /metrics is a
scrape away from gone, and a crashed process takes both with it. This
module hooks the two edges where state is about to be lost — the SLO
watchdog's alert edge (``telemetry.alert_event`` → ``_flight_alert``)
and the multihost crash path (the heartbeat excepthook/atexit and the
monitor's pre-``os._exit`` host-loss branch) — and writes ONE atomic
JSON bundle per trigger under ``MXNET_FLIGHTREC_DIR``: the triggering
alert, the last K telemetry records (a shadow ring — the run's own
records leave memory at every sink flush), the trace-ring tail,
``envs.snapshot()``, ``compile_watch.site_stats()``, the latest
serving/decode/router snapshots, and the fleet topology (rank/world/
restart generation, replica roster).

Discipline mirrors the rest of the observability stack:

- **Always cheap when off** — arming installs two module-global hooks
  in telemetry (``_recent``, ``_flight_alert``); disarmed, every hook
  is one ``None`` check and no sink byte changes.
- **Bounded** — at most ``MXNET_FLIGHTREC_MAX_BUNDLES`` bundles and
  ``MXNET_FLIGHTREC_MAX_BYTES`` on disk (oldest deleted first), one
  dump per ``MXNET_FLIGHTREC_INTERVAL_MS`` (an alert storm suppresses,
  never stacks; crash dumps bypass the interval — they are the last
  chance), trace tail capped at :data:`_TRACE_TAIL_EVENTS` events.
- **Never fatal** — a dump visits the ``flightrec`` fault site and
  swallows every exception as a counted failure: the recorder must
  not take down the process it is post-morteming.

``python -m mxnet_tpu.tools.diagnose <dir>`` renders each bundle as a
one-line summary next to the fleet report.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

from . import envs

__all__ = ["enabled", "enable", "disable", "maybe_enable", "stats",
           "on_alert", "crash_dump", "dump", "BUNDLE_PREFIX",
           "read_bundle", "list_bundles"]

BUNDLE_PREFIX = "flightrec-"
_TRACE_TAIL_EVENTS = 5000       # trace-ring tail kept per bundle

_rec = None            # the armed _Recorder; module-global None check
_lock = threading.Lock()
_log = logging.getLogger(__name__)


class _Recorder:
    def __init__(self, dirname):
        self.dir = dirname
        self.max_bundles = max(
            1, envs.get_int("MXNET_FLIGHTREC_MAX_BUNDLES"))
        self.max_bytes = max(
            1 << 16, envs.get_int("MXNET_FLIGHTREC_MAX_BYTES"))
        self.interval_s = max(
            0, envs.get_int("MXNET_FLIGHTREC_INTERVAL_MS")) / 1e3
        self.recent = deque(maxlen=max(
            1, envs.get_int("MXNET_FLIGHTREC_RECORDS")))
        self.seq = 0
        self.dumps = 0
        self.suppressed = 0
        self.failed = 0
        # first trigger always dumps: the rate limit bounds storms,
        # not the first sighting
        self.last_dump = None


def enabled():
    """True while the recorder is armed."""
    return _rec is not None


def enable(dirname=None):
    """Arm the recorder (idempotent): bundles land under ``dirname``
    (or ``MXNET_FLIGHTREC_DIR``), the telemetry shadow ring and the
    alert-edge hook are installed. Returns the bundle directory."""
    global _rec
    from . import telemetry
    with _lock:
        if _rec is not None:
            return _rec.dir
        dirname = dirname or envs.get_path("MXNET_FLIGHTREC_DIR")
        if not dirname:
            raise ValueError("flightrec.enable: no directory — pass "
                             "dirname= or set MXNET_FLIGHTREC_DIR")
        os.makedirs(dirname, exist_ok=True)
        _rec = _Recorder(dirname)
        telemetry._recent = _rec.recent
        telemetry._flight_alert = on_alert
        return _rec.dir


def disable():
    """Disarm: uninstall the telemetry hooks. Returns final
    :func:`stats` (or None when never armed)."""
    global _rec
    from . import telemetry
    with _lock:
        rec, _rec = _rec, None
        if rec is None:
            return None
        telemetry._recent = None
        telemetry._flight_alert = None
        return {"dir": rec.dir, "dumps": rec.dumps,
                "suppressed": rec.suppressed, "failed": rec.failed}


def maybe_enable():
    """Arm when ``MXNET_FLIGHTREC_DIR`` is set — called from
    ``telemetry.start`` so the recorder rides a run the way tracing
    does. Returns True when armed after the call."""
    if _rec is not None:
        return True
    if envs.get_path("MXNET_FLIGHTREC_DIR"):
        enable()
        return True
    return False


def stats():
    """{"dir", "dumps", "suppressed", "failed"}; None when off."""
    rec = _rec
    if rec is None:
        return None
    return {"dir": rec.dir, "dumps": rec.dumps,
            "suppressed": rec.suppressed, "failed": rec.failed}


# ---------------------------------------------------------------------------
# triggers
# ---------------------------------------------------------------------------

def on_alert(alert):
    """The SLO-watchdog alert edge (installed as
    ``telemetry._flight_alert``): one bundle per alert, rate-limited."""
    dump("alert", alert=alert)


def crash_dump(reason, detail=None):
    """The crash path (multihost heartbeat excepthook / host-loss
    monitor): bypasses the rate limit — a dying process gets its last
    word regardless of how recently an alert dumped."""
    extra = {"detail": detail} if detail else None
    return dump("crash:%s" % reason, extra=extra, force=True)


def dump(reason, alert=None, extra=None, force=False):
    """Write one bundle. Returns the bundle path, or None when the
    recorder is off, the rate limit suppressed the dump, or the dump
    failed (counted, logged, never raised)."""
    rec = _rec
    if rec is None:
        return None
    with _lock:
        if rec is not _rec:
            return None
        now = time.monotonic()
        if (not force and rec.last_dump is not None
                and now - rec.last_dump < rec.interval_s):
            rec.suppressed += 1
            return None
        rec.last_dump = now
        rec.seq += 1
        seq = rec.seq
    try:
        return _write_bundle(rec, seq, reason, alert, extra)
    except Exception as exc:               # noqa: BLE001 — see module
        # doc: the recorder must never take down the host process;
        # InjectedFault from the drill site lands here too
        rec.failed += 1
        _log.warning("flightrec: dump failed (%s: %s)",
                     type(exc).__name__, str(exc)[:200])
        return None


# ---------------------------------------------------------------------------
# bundle assembly
# ---------------------------------------------------------------------------

def _identity():
    from . import tracing
    ident = tracing.process_identity()
    world = os.environ.get("DMLC_NUM_WORKER", "")
    if not world:
        world = envs.get_int("MXNET_TPU_WORLD") or 1
    try:
        ident["world"] = int(world)
    except (TypeError, ValueError):
        ident["world"] = 1
    ident["pid"] = os.getpid()
    return ident


def _versions():
    out = {}
    try:
        import jax
        out["jax"] = getattr(jax, "__version__", None)
        import jaxlib
        out["jaxlib"] = getattr(jaxlib, "__version__", None)
    except Exception:                      # noqa: BLE001 — advisory
        pass
    return out


def _write_bundle(rec, seq, reason, alert, extra):
    from . import compile_watch, fault, metering, telemetry, tracing
    fault.inject("flightrec")        # the deterministic dumper drill
    run = telemetry._run or telemetry._last_run
    bundle = {
        "type": "flightrec",
        "version": 1,
        "reason": reason,
        "time": time.time(),
        "identity": _identity(),
        "versions": _versions(),
        "alert": dict(alert) if alert else None,
        "records": list(rec.recent),
        "envs": envs.snapshot(),
        "compile_sites": compile_watch.site_stats(),
        "fault": fault.stats(),
        "trace_stats": tracing.stats(),
        # who-was-being-billed at the crash edge: the meter's
        # cumulative per-tenant books (None when metering is off —
        # the key stays so bundle readers need no probing)
        "metering": metering.snapshot(),
    }
    if extra:
        bundle.update(extra)
    if run is not None:
        # advisory reads — trace metadata, not accounting; the latest
        # cumulative snapshots double as the fleet topology (replica
        # roster with states rides every router snapshot)
        bundle["run"] = {"run_id": run.run_id, "steps": run.steps,
                         "alerts_dropped": run.alerts_dropped}
        bundle["alerts"] = list(run.alerts or [])
        bundle["serving"] = run.serving
        bundle["decode"] = run.decode
        bundle["router"] = run.router
        routers = run.router or {}
        bundle["topology"] = {
            name: [dict(r) for r in (snap.get("replicas") or [])]
            for name, snap in routers.items()}
    if tracing.enabled():
        trace = tracing.export()
        evs = trace["traceEvents"]
        if len(evs) > _TRACE_TAIL_EVENTS:
            # keep metadata rows + the newest tail: the ring is
            # newest-wins and so is the bundle
            metas = [e for e in evs if e.get("ph") == "M"]
            tail = [e for e in evs if e.get("ph") != "M"]
            trace["traceEvents"] = metas + tail[-_TRACE_TAIL_EVENTS:]
            trace["otherData"]["bundle_truncated_events"] = \
                len(tail) - _TRACE_TAIL_EVENTS
        bundle["trace"] = trace
    payload = json.dumps(bundle)
    _rotate(rec, len(payload))
    stamp = time.strftime("%Y%m%dT%H%M%S",
                          time.gmtime(bundle["time"]))
    path = os.path.join(rec.dir, "%s%s-%d-%03d.json"
                        % (BUNDLE_PREFIX, stamp, os.getpid(), seq))
    tmp = "%s.%d.tmp" % (path, os.getpid())
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, path)
    rec.dumps += 1
    return path


def _rotate(rec, incoming_bytes):
    """Delete oldest bundles until the new one fits the count and
    byte budgets. Oldest = lexicographically first (the UTC-stamped
    names sort by time)."""
    try:
        names = sorted(n for n in os.listdir(rec.dir)
                       if n.startswith(BUNDLE_PREFIX)
                       and n.endswith(".json"))
    except OSError:
        return
    sizes = {}
    for n in names:
        try:
            sizes[n] = os.path.getsize(os.path.join(rec.dir, n))
        except OSError:
            sizes[n] = 0
    total = sum(sizes.values())
    while names and (len(names) >= rec.max_bundles
                     or total + incoming_bytes > rec.max_bytes):
        victim = names.pop(0)
        total -= sizes.get(victim, 0)
        try:
            os.unlink(os.path.join(rec.dir, victim))
        except OSError:
            pass


# ---------------------------------------------------------------------------
# readers (diagnose / tests)
# ---------------------------------------------------------------------------

def list_bundles(dirname):
    """Bundle paths under ``dirname``, oldest first."""
    try:
        names = sorted(n for n in os.listdir(dirname)
                       if n.startswith(BUNDLE_PREFIX)
                       and n.endswith(".json"))
    except OSError:
        return []
    return [os.path.join(dirname, n) for n in names]


def read_bundle(path):
    """Load one bundle dict (raises on unreadable/torn files — the
    diagnose caller counts those as warnings)."""
    with open(path) as f:
        return json.load(f)
