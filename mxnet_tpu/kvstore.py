"""KVStore — parameter synchronization (parity: python/mxnet/kvstore.py
+ src/kvstore/).

Types (factory semantics mirror kvstore.cc:40 substring matching):

- ``local`` / ``device`` — single-process aggregation. The reference
  reduces across GPU copies (CommCPU/CommDevice, comm.h); here values
  live as single (possibly mesh-sharded) arrays, so Reduce is a tree-sum
  of the pushed list compiled by XLA.
- ``tpu_sync`` (also matches ``dist_sync`` / ``dist_device_sync``) — the
  SURVEY §5.8 north star: push/pull lower to psum collectives over the
  ICI mesh via jax.distributed rank/size when launched multi-process,
  replacing the ps-lite ZPush/ZPull path wholesale.
- ``dist_async`` — accepted; degrades to sync (documented divergence,
  SURVEY §2.2 Async SGD row).

``update_on_kvstore`` semantics, optimizer/updater hosting, row_sparse
pull, and gradient-compression API parity are kept.
"""
from __future__ import annotations

import pickle

from .base import MXNetError
from . import optimizer as opt
from .ndarray import NDArray

__all__ = ["KVStore", "create"]


def _ctype_key_value(key, vals):
    if isinstance(key, (tuple, list)):
        return list(key), list(vals)
    return [key], [vals]


class KVStore:
    """Key-value store for parameter synchronization
    (reference: kvstore.py:61)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._data = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._is_dist = ("dist" in kv_type) or ("tpu" in kv_type)

    # -- identity --------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        import jax
        try:
            return jax.process_index()
        except Exception:
            return 0

    @property
    def num_workers(self):
        import jax
        try:
            return jax.process_count()
        except Exception:
            return 1

    # -- core ops --------------------------------------------------------
    def init(self, key, value):
        keys, vals = _ctype_key_value(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                v = v[0]
            self._data[k] = v.copy()

    def push(self, key, value, priority=0):
        """Aggregate value(s) into the store.

        Single-device-list push: tree-sum (the CommDevice Reduce role).
        On multi-process tpu_sync, the sum additionally runs a psum
        across processes via jax collectives.
        """
        keys, vals = _ctype_key_value(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                agg = v[0]
                for other in v[1:]:
                    agg = agg + other
            else:
                agg = v
            agg = self._global_reduce(agg)
            if self._optimizer is not None:
                self._ensure_updater()
            if self._updater is not None:
                self._updater(self._key_index(k), agg, self._data[k])
            else:
                # KVStoreLocal without updater: merged value replaces the
                # stored one (kvstore_local.h PushImpl assign semantics)
                self._data[k] = agg.copy()

    def _global_reduce(self, arr):
        if not self._is_dist or self.num_workers == 1:
            return arr
        import jax
        import jax.numpy as jnp
        # cross-process allreduce over all participating hosts: use
        # jax.make_array / process_allgather via multihost_utils
        from jax.experimental import multihost_utils
        summed = multihost_utils.process_allgather(arr._data)
        return NDArray(jnp.sum(summed, axis=0), ctx=arr._ctx)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = _ctype_key_value(key, out)
        for k, o in zip(keys, outs):
            if k not in self._data:
                raise MXNetError("kvstore: key %s not initialized" % str(k))
            v = self._data[k]
            if isinstance(o, (list, tuple)):
                for oo in o:
                    oo._set_data(v._data)
            else:
                o._set_data(v._data)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull selected rows (reference: kvstore.py row_sparse_pull →
        kvstore_dist.h EncodeRowSparseKey). Dense-gather implementation."""
        assert out is not None and row_ids is not None
        keys, outs = _ctype_key_value(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        for k, o, rid in zip(keys, outs, row_ids):
            v = self._data[k]
            rows = v.take(rid)
            tgt = o if not isinstance(o, (list, tuple)) else o[0]
            from .ndarray import sparse as _sp
            if hasattr(tgt, "indices"):
                tgt._set_rows(rid, rows)
            else:
                tgt._set_data(rows._data)

    # -- updater/optimizer ----------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _updater_func = property(lambda self: self._updater)

    def set_optimizer(self, optimizer):
        """Host the optimizer kvstore-side (update_on_kvstore=True path;
        reference runs it server-side, kvstore_dist_server.h:346)."""
        self._optimizer = optimizer
        self._ensure_updater()

    def _ensure_updater(self):
        if self._updater is None and self._optimizer is not None:
            self._updater = opt.get_updater(self._optimizer)

    def _key_index(self, key):
        if not hasattr(self, "_key_order"):
            self._key_order = {}
        if key not in self._key_order:
            self._key_order[key] = len(self._key_order)
        return self._key_order[key]

    # -- gradient compression -------------------------------------------
    def set_gradient_compression(self, compression_params):
        """API parity (reference: gradient_compression.h). On ICI the
        allreduce is already on-chip; compression recorded as metadata."""
        if "type" not in compression_params:
            raise ValueError("compression_params requires 'type'")
        self._compression_params = dict(compression_params)

    # -- distributed control --------------------------------------------
    def barrier(self):
        if self.num_workers > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("kvstore_barrier")

    def _barrier(self):
        self.barrier()

    def _send_command_to_servers(self, head, body):
        pass

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for " \
            "distributed training without updater"
        with open(fname, 'wb') as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for " \
            "distributed training without updater"
        self._updater.set_states(open(fname, 'rb').read())


def create(name='local'):
    """Factory (reference: kvstore.py:649; type matching kvstore.cc:40)."""
    if not isinstance(name, str):
        raise TypeError('name must be a string')
    if name not in ('local', 'device', 'nccl', 'tpu_sync', 'dist_sync',
                    'dist_device_sync', 'dist_async', 'dist'):
        # substring semantics like the reference factory
        if not any(t in name for t in ('local', 'device', 'dist', 'tpu')):
            raise MXNetError("unknown KVStore type %s" % name)
    return KVStore(name)
