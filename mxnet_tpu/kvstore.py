"""KVStore — parameter synchronization (parity: python/mxnet/kvstore.py
+ src/kvstore/).

Types (factory semantics mirror kvstore.cc:40 substring matching):

- ``local`` / ``device`` — single-process aggregation. The reference
  reduces across GPU copies (CommCPU/CommDevice, comm.h); here values
  live as single (possibly mesh-sharded) arrays, so Reduce is a tree-sum
  of the pushed list compiled by XLA.
- ``tpu_sync`` (also matches ``dist_sync`` / ``dist_device_sync``) — the
  SURVEY §5.8 north star: push/pull lower to psum collectives over the
  ICI mesh via jax.distributed rank/size when launched multi-process,
  replacing the ps-lite ZPush/ZPull path wholesale.
- ``dist_async`` — accepted; degrades to sync (documented divergence,
  SURVEY §2.2 Async SGD row), announced by a one-time warning.

``update_on_kvstore`` semantics, optimizer/updater hosting, row_sparse
pull, and gradient-compression API parity are kept.

Fault tolerance (see README "Fault tolerance" + ``mxnet_tpu.fault``):
dist-type push/pull run under ``fault.with_retries`` — transient
transport errors and planned faults (``MXNET_FAULT_PLAN`` sites
``push``/``pull``/``allreduce``/``init``) are retried with exponential
backoff, and a persistently failing op raises
``CollectiveTimeoutError`` after ``MXNET_KVSTORE_TIMEOUT`` instead of
erroring out on the first attempt. Caveat: retrying a CROSS-PROCESS
collective is only coordinated when the fault is symmetric (a planned
fault fires on every worker running the same plan; real one-sided
transport errors need the symmetric retry barrier a later elastic PR
adds) — the proven lanes are the single-process degenerate case and
planned-fault chaos runs.

Observability: with a telemetry run active (``mxnet_tpu.telemetry``),
every push/pull is accounted per key — bytes moved and caller-observed
latency (retry backoff included) — under comm kinds ``push``/``pull``.
"""
from __future__ import annotations

import functools
import logging
import pickle

from . import fault
from . import telemetry
from .base import MXNetError
from . import optimizer as opt
from .ndarray import NDArray

__all__ = ["KVStore", "create"]


def _to_jnp(np_arr):
    import jax.numpy as jnp
    return jnp.asarray(np_arr)


def _canonical_index_dtype():
    from .util import canonical_dtype
    import numpy as _np
    return canonical_dtype(_np.int64)


def _ctype_key_value(key, vals):
    if isinstance(key, (tuple, list)):
        return list(key), list(vals)
    return [key], [vals]


class _TwoBitCompressor:
    """Threshold quantizer with per-key error feedback (the worker side
    of ref gradient_compression.h: Quantize2Bit + residual kept local).
    Values land in {-t, 0, +t}; the dropped mass feeds the next push."""

    def __init__(self, threshold):
        if threshold <= 0:
            raise ValueError("2bit compression threshold must be > 0")
        self.threshold = threshold
        self._residual = {}

    def compress(self, key, arr):
        import jax.numpy as jnp
        t = self.threshold
        x = arr._data
        res = self._residual.get(key)
        if res is not None:
            x = x + res
        q = jnp.where(x >= t, jnp.asarray(t, x.dtype),
                      jnp.where(x <= -t, jnp.asarray(-t, x.dtype),
                                jnp.zeros((), x.dtype)))
        self._residual[key] = x - q
        return NDArray(q, ctx=arr._ctx)


def _ensure_process_group():
    """A dist kvstore created in a worker spawned by ``python -m
    mxnet_tpu.tools.launch -n N ...`` joins the DMLC_* process group
    (fault.join_process_group — retrying, shared with package import);
    a process already in a group (manual initialize, TPU pod runtime)
    or with no contract in the env is left untouched."""
    import jax
    try:
        if jax.process_count() > 1:
            return
    except Exception:
        pass
    fault.join_process_group()


_DIST_ASYNC_WARNED = False


def _warn_dist_async_once():
    """dist_async degrades to synchronous updates on this backend (the
    documented divergence, SURVEY §2.2 Async SGD row) — say so once
    instead of silently changing semantics."""
    global _DIST_ASYNC_WARNED
    if not _DIST_ASYNC_WARNED:
        _DIST_ASYNC_WARNED = True
        logging.warning(
            "kvstore 'dist_async' degrades to synchronous updates on "
            "this backend (documented divergence, SURVEY §2.2 Async SGD "
            "row): pushes are psum-reduced across workers like "
            "'tpu_sync', with the same retry/timeout guarding.")


class KVStore:
    """Key-value store for parameter synchronization
    (reference: kvstore.py:61)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._data = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._is_dist = ("dist" in kv_type) or ("tpu" in kv_type)
        if self._is_dist:
            if "async" in kv_type:
                _warn_dist_async_once()
            _ensure_process_group()

    # -- identity --------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        import jax
        try:
            return jax.process_index()
        except Exception:
            return 0

    @property
    def num_workers(self):
        import jax
        try:
            return jax.process_count()
        except Exception:
            return 1

    # -- core ops --------------------------------------------------------
    def init(self, key, value):
        keys, vals = _ctype_key_value(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                v = v[0]
            self._data[k] = v.copy()

    def _guarded(self, fn, site):
        """Run one sync phase under fault.with_retries on dist stores
        (and whenever a fault plan is active); the local fast path
        stays a direct call. Callers keep state mutation OUT of the
        retried region — the injection point fires at the top of each
        attempt, and only communication re-runs on failure."""
        if self._is_dist:
            return fault.with_retries(fn, site=site)
        return fault.guard(fn, site)

    def push(self, key, value, priority=0):
        """Aggregate value(s) into the store.

        Single-device-list push: tree-sum (the CommDevice Reduce role).
        On multi-process tpu_sync, the sum additionally runs a psum
        across processes via jax collectives.
        """
        keys, vals = _ctype_key_value(key, value)
        for k, v in zip(keys, vals):
            self._push_one(k, v)

    def _push_one(self, k, v):
        # local phase — aggregation and compression mutate worker-local
        # state (compression residual), so they run exactly once
        if isinstance(v, (list, tuple)):
            # CommDevice semantics (comm.h:451): gather the
            # per-device copies onto the first device's placement,
            # then tree-sum there (XLA fuses the adds).
            vs = [v[0]] + [self._like(x, v[0]) for x in v[1:]]
            agg = self._tree_sum(vs)
        else:
            agg = v
        comp = getattr(self, "_compression", None)
        if comp is not None:
            from .ndarray.sparse import BaseSparseNDArray
            if not isinstance(agg, BaseSparseNDArray):
                agg = comp.compress(k, agg)
        # communication phase — the only retried region; re-running the
        # reduce is free of side effects on this worker. The telemetry
        # latency is caller-observed: retry backoff counts.
        with telemetry.comm_span("push", k, agg):
            agg = self._guarded(
                functools.partial(self._global_reduce, agg), site="push")
        # apply phase — runs at most once per push, so a retried
        # transport failure can never double-apply an optimizer update
        if self._optimizer is not None:
            self._ensure_updater()
        if self._updater is not None:
            self._align_placement(agg, self._data[k])
            self._updater(self._key_index(k), agg, self._data[k])
        else:
            # KVStoreLocal without updater: merged value replaces the
            # stored one (kvstore_local.h PushImpl assign semantics)
            self._data[k] = agg.copy()

    @staticmethod
    def _tree_sum(vals):
        """The Reduce kernel of a list-push (CommDevice Reduce role,
        comm.h:451): sum the per-worker copies. Works on NDArrays or raw
        device arrays and is jit-traceable, so bench.py can scan the
        SAME aggregation program the kvstore compiles."""
        agg = vals[0]
        for other in vals[1:]:
            agg = agg + other
        return agg

    @staticmethod
    def _like(arr, ref):
        """arr re-placed onto ref's sharding (no-op when it matches)."""
        from .ndarray.sparse import BaseSparseNDArray
        if isinstance(arr, BaseSparseNDArray) \
                or isinstance(ref, BaseSparseNDArray):
            return arr  # sparse values carry their own placement
        if getattr(arr._data, "sharding", None) == \
                getattr(ref._data, "sharding", None):
            return arr
        import jax
        return NDArray(jax.device_put(arr._data, ref._data.sharding),
                       ctx=ref._ctx)

    def _align_placement(self, pushed, stored):
        """Move the stored value onto the pushed gradient's sharding when
        they differ — a dp-mesh executor pushes replicated global arrays
        while kvstore copies were made pre-mesh on one device, and jax
        refuses eager math across device sets."""
        from .ndarray.sparse import BaseSparseNDArray
        if isinstance(pushed, BaseSparseNDArray) \
                or isinstance(stored, BaseSparseNDArray):
            return
        p, s = pushed._data, stored._data
        ps = getattr(p, "sharding", None)
        ss = getattr(s, "sharding", None)
        if ps is not None and ss is not None and ps != ss:
            import jax
            stored._set_data(jax.device_put(s, ps))

    def _global_reduce(self, arr):
        """Cross-process allreduce for tpu_sync (SURVEY §5.8 north star).

        On backends with cross-process SPMD (TPU pods) the reduce runs
        IN-PROGRAM: each worker's value becomes one shard of a global
        array over a 'worker' mesh axis and a single jitted psum (XLA
        collective over ICI/DCN) produces the sum — replacing the
        reference's ps-lite ZPush/ZPull round trip
        (kvstore_dist.h:211). Backends without it (jaxlib's CPU
        backend refuses multiprocess computations) exchange through
        the process group's coordination service
        (``parallel.multihost.cross_host_sum``): rank-keyed gathers +
        a deterministic rank-order fold — the same channel the ps-lite
        server pool occupied, minus the server processes. Either way
        the bytes land in the per-link (ici/dcn) telemetry split.
        """
        if not self._is_dist or self.num_workers == 1:
            return arr
        from .ndarray.sparse import BaseSparseNDArray, RowSparseNDArray
        if isinstance(arr, RowSparseNDArray):
            return self._global_reduce_rsp(arr)
        if isinstance(arr, BaseSparseNDArray):
            # CSR is not a reference dist-push format (the server merge
            # at kvstore_dist_server.h:499 is rsp-only); dense roundtrip
            stype = arr.stype
            return self._global_reduce(arr.tostype("default")) \
                .tostype(stype)
        import jax
        import numpy as _np
        from .parallel import multihost
        if getattr(self, "_inprogram_reduce", None) is None:
            self._inprogram_reduce = multihost.supports_global_spmd()
        if self._inprogram_reduce:
            try:
                from jax.sharding import Mesh, PartitionSpec as P
                from jax.experimental import multihost_utils
                from .parallel import collectives

                # one device per process carries that worker's shard
                per_proc = {}
                for d in jax.devices():
                    per_proc.setdefault(d.process_index, d)
                workers = [per_proc[i] for i in sorted(per_proc)]
                mesh = Mesh(_np.asarray(workers), ("worker",))
                local = arr._data[None]  # (1, ...) local shard
                glob = multihost_utils.host_local_array_to_global_array(
                    local, mesh, P("worker"))
                summed = collectives.all_reduce(glob, mesh, axis="worker")
                # back to a process-local array before any eager math
                local_sum = multihost_utils.global_array_to_host_local_array(
                    summed, mesh, P())
                return NDArray(local_sum[0], ctx=arr._ctx)
            except Exception as exc:
                # disable for the rest of the run so every push doesn't
                # re-raise; the host roundtrip is correct but slow, and
                # silence would hide that the fast path is dead
                import warnings
                warnings.warn(
                    "kvstore %s: in-program collective reduce failed "
                    "(%s: %s); falling back to the coordination-"
                    "service exchange for all subsequent pushes"
                    % (self._type, type(exc).__name__, exc))
                self._inprogram_reduce = False
        local = _np.asarray(arr._data)[None]      # (1, ...) local row
        total = multihost.cross_host_sum("kv_push", [local])[0]
        telemetry.comm_links("kvstore_push", 0,
                             int(local.nbytes) * (self.num_workers - 1))
        return NDArray(_to_jnp(total), ctx=arr._ctx)

    def _global_reduce_rsp(self, arr):
        """Row-union cross-worker reduce for row_sparse values — the
        TPU-native form of the reference server's rsp merge
        (kvstore_dist_server.h:499 ApplyUpdates row union).

        Workers exchange ONE bool presence mask per row (N bools, not
        N*D values), deterministically agree on the sorted union of
        touched rows, scatter their local rows onto union slots, and
        allreduce only the (U, D) union block — the embedding-gradient
        value never densifies to (N, D)."""
        import numpy as _np
        import jax.numpy as jnp
        from .ndarray.sparse import RowSparseNDArray
        from .parallel import multihost

        N = int(arr.shape[0])
        row_shape = tuple(arr.shape[1:])
        idx = arr._sp_indices._data
        mask = jnp.zeros((N,), jnp.bool_).at[idx].set(True)
        # presence masks ride the coordination service (N bools per
        # worker — control-plane-sized on every backend)
        masks = _np.stack([m[0] for m in multihost.exchange_arrays(
            "kv_rsp_mask", [_np.asarray(mask)])])           # (W, N)
        union = _np.nonzero(masks.any(axis=0))[0] \
            .astype(_np.int64)                              # sorted
        dtype = arr._sp_data._data.dtype
        if union.size == 0:
            return RowSparseNDArray(
                NDArray(jnp.zeros((0,) + row_shape, dtype),
                        ctx=arr._ctx),
                NDArray(jnp.zeros(
                    (0,), _canonical_index_dtype()), ctx=arr._ctx),
                arr.shape, ctx=arr._ctx)
        pos = jnp.searchsorted(jnp.asarray(union), idx)
        local = jnp.zeros((union.shape[0],) + row_shape, dtype) \
            .at[pos].add(arr._sp_data._data)
        summed = self._global_reduce(NDArray(local, ctx=arr._ctx))
        return RowSparseNDArray(
            summed, NDArray(jnp.asarray(union), ctx=arr._ctx),
            arr.shape, ctx=arr._ctx)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = _ctype_key_value(key, out)
        for k, o in zip(keys, outs):
            with telemetry.comm_span("pull", k, self._data.get(k)):
                self._guarded(
                    functools.partial(self._pull_one, k, o,
                                      ignore_sparse),
                    site="pull")

    def _pull_one(self, k, o, ignore_sparse):
        from .ndarray.sparse import BaseSparseNDArray
        if k not in self._data:
            raise MXNetError("kvstore: key %s not initialized" % str(k))
        v = self._data[k]
        if isinstance(v, BaseSparseNDArray):
            if ignore_sparse:
                return  # reference pull skips sparse values
            tgts = o if isinstance(o, (list, tuple)) else [o]
            for tgt in tgts:
                v.copyto(tgt)
            return
        if isinstance(o, (list, tuple)):
            # Broadcast: each destination keeps its own placement
            # (comm.h Broadcast copies back out to every device).
            for oo in o:
                oo._set_data(self._like(v, oo)._data)
        else:
            o._set_data(self._like(v, o)._data)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows of a value (reference:
        kvstore.py row_sparse_pull → kvstore_dist.h EncodeRowSparseKey).

        The stored value's selected rows are gathered on-device; the
        returned row set is deduplicated and sorted, as the reference
        guarantees. ``out`` must be row_sparse (the reference asserts
        the same); a dense ``out`` raises MXNetError.
        """
        import numpy as _host_np
        from .ndarray.sparse import RowSparseNDArray, BaseSparseNDArray
        assert out is not None and row_ids is not None
        keys, outs = _ctype_key_value(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        for k, o, rid in zip(keys, outs, row_ids):
            v = self._data[k]
            if isinstance(v, BaseSparseNDArray):
                v = v.tostype("default")
            rid_np = _host_np.unique(
                rid.asnumpy().astype(_host_np.int64)
                if isinstance(rid, NDArray)
                else _host_np.asarray(rid, dtype=_host_np.int64))
            rid_nd = NDArray(_to_jnp(rid_np), ctx=v._ctx)
            rows = v.take(rid_nd)
            tgts = o if isinstance(o, (list, tuple)) else [o]
            for tgt in tgts:
                if isinstance(tgt, RowSparseNDArray):
                    tgt._sp_data = rows.copy()
                    tgt._sp_indices = NDArray(_to_jnp(rid_np),
                                              ctx=v._ctx)
                    tgt._shape = v.shape
                else:
                    # reference asserts the out stype is row_sparse
                    # (kvstore.py row_sparse_pull); a dense out would
                    # silently get a (len(row_ids), D) buffer in place
                    # of its declared full shape.
                    raise MXNetError(
                        "row_sparse_pull requires 'out' arrays with "
                        "stype='row_sparse', got dense NDArray for key "
                        "%s" % (k,))

    # -- updater/optimizer ----------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _updater_func = property(lambda self: self._updater)

    def set_optimizer(self, optimizer):
        """Host the optimizer kvstore-side (update_on_kvstore=True path;
        reference runs it server-side, kvstore_dist_server.h:346)."""
        self._optimizer = optimizer
        self._ensure_updater()

    def _ensure_updater(self):
        if self._updater is None and self._optimizer is not None:
            self._updater = opt.get_updater(self._optimizer)

    def _key_index(self, key):
        if not hasattr(self, "_key_order"):
            self._key_order = {}
        if key not in self._key_order:
            self._key_order[key] = len(self._key_order)
        return self._key_order[key]

    # -- gradient compression -------------------------------------------
    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression with worker-side error feedback
        (reference: src/kvstore/gradient_compression.h:52). Each push
        quantizes grad+residual to {-threshold, 0, +threshold} before
        the cross-worker reduce — 2 bits of information per element on
        the wire — and keeps the quantization error as the residual
        added to the next push, the reference's feedback loop."""
        if "type" not in compression_params:
            raise ValueError("compression_params requires 'type'")
        ctype = compression_params["type"]
        if ctype not in ("2bit", "none"):
            raise ValueError(
                "unsupported gradient compression type %r (2bit|none)"
                % (ctype,))
        self._compression_params = dict(compression_params)
        if ctype == "2bit":
            self._compression = _TwoBitCompressor(
                float(compression_params.get("threshold", 0.5)))
        else:
            self._compression = None

    # -- distributed control --------------------------------------------
    def barrier(self):
        if self.num_workers > 1:
            # device sync where the backend can span processes,
            # coordination-service barrier where it cannot (CPU)
            from .parallel import distributed
            distributed.barrier("kvstore_barrier")

    def _barrier(self):
        self.barrier()

    def _send_command_to_servers(self, head, body):
        pass

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for " \
            "distributed training without updater"
        from .base import atomic_write_bytes
        atomic_write_bytes(fname, self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for " \
            "distributed training without updater"
        self._updater.set_states(open(fname, 'rb').read())


def create(name='local'):
    """Factory (reference: kvstore.py:649; type matching kvstore.cc:40)."""
    if not isinstance(name, str):
        raise TypeError('name must be a string')
    if name not in ('local', 'device', 'nccl', 'tpu_sync', 'dist_sync',
                    'dist_device_sync', 'dist_async', 'dist'):
        # substring semantics like the reference factory
        if not any(t in name for t in ('local', 'device', 'dist', 'tpu')):
            raise MXNetError("unknown KVStore type %s" % name)
    return KVStore(name)
