"""ctypes bindings for the native IO runtime (native/io/recordio_io.cc
— the C++ data-plane counterpart of the reference's src/io/: buffered
RecordIO frame reading + a dmlc::ThreadedIter-style prefetch thread).

The library is optional: ``available()`` is False when
``native/build/libmxtpu_io.so`` has not been built (``make -C
native``), and every consumer falls back to the pure-Python
``mxnet_tpu.recordio`` path. ``MXNET_USE_NATIVE_IO=0`` disables it
explicitly.
"""
from __future__ import annotations

import ctypes
import os

from .. import envs

__all__ = ["available", "lib_path", "NativeRecordReader",
           "PrefetchingRecordReader"]

_LIB = None
_TRIED = False


def lib_path():
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "native", "build", "libmxtpu_io.so")


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if not envs.get_bool("MXNET_USE_NATIVE_IO"):
        return None
    path = lib_path()
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    for prefix in ("mxtpu_rec", "mxtpu_prefetch"):
        getattr(lib, prefix + "_open").restype = ctypes.c_void_p
        nxt = getattr(lib, prefix + "_next")
        nxt.restype = ctypes.c_int
        nxt.argtypes = [ctypes.c_void_p, ctypes.POINTER(u8p),
                        ctypes.POINTER(ctypes.c_uint64)]
        getattr(lib, prefix + "_error").restype = ctypes.c_char_p
        getattr(lib, prefix + "_error").argtypes = [ctypes.c_void_p]
        getattr(lib, prefix + "_close").argtypes = [ctypes.c_void_p]
    lib.mxtpu_rec_open.argtypes = [ctypes.c_char_p]
    lib.mxtpu_rec_seek.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.mxtpu_prefetch_open.argtypes = [ctypes.c_char_p,
                                        ctypes.c_uint64]
    _LIB = lib
    return _LIB


def available():
    return _load() is not None


class _ReaderBase:
    _prefix = None

    def __init__(self, handle):
        self._h = handle
        self._lib = _load()

    def _next(self):
        u8p = ctypes.POINTER(ctypes.c_uint8)
        data = u8p()
        length = ctypes.c_uint64()
        rc = getattr(self._lib, self._prefix + "_next")(
            self._h, ctypes.byref(data), ctypes.byref(length))
        if rc == 0:
            return None
        if rc < 0:
            err = getattr(self._lib, self._prefix + "_error")(self._h)
            raise RuntimeError((err or b"native IO error").decode())
        return ctypes.string_at(data, length.value)

    def read(self):
        """One record's payload bytes, or None at end of stream —
        the MXRecordIO.read contract."""
        return self._next()

    def __iter__(self):
        while True:
            rec = self._next()
            if rec is None:
                return
            yield rec

    def close(self):
        if self._h is not None:
            getattr(self._lib, self._prefix + "_close")(self._h)
            self._h = None

    __enter__ = lambda self: self
    __exit__ = lambda self, *exc: self.close()
    __del__ = lambda self: self.close()


class NativeRecordReader(_ReaderBase):
    """Sequential buffered .rec reader over the native library."""

    _prefix = "mxtpu_rec"

    def __init__(self, path):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native IO library not built; run `make -C native` "
                "or use mxnet_tpu.recordio.MXRecordIO")
        h = lib.mxtpu_rec_open(os.fsencode(path))
        if not h:
            raise IOError("cannot open %s" % path)
        super().__init__(h)
        self._path = path

    def seek(self, offset):
        self._lib.mxtpu_rec_seek(self._h, int(offset))

    def reset(self):
        self.seek(0)


class PrefetchingRecordReader(_ReaderBase):
    """Background-thread prefetching reader (the PrefetcherIter /
    dmlc::ThreadedIter role, ref iter_prefetcher.h:47): a C++ producer
    thread stays ahead of the consumer up to ``capacity_bytes``."""

    _prefix = "mxtpu_prefetch"

    def __init__(self, path, capacity_bytes=64 << 20):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native IO library not built; run `make -C native`")
        h = lib.mxtpu_prefetch_open(os.fsencode(path),
                                    int(capacity_bytes))
        if not h:
            raise IOError("cannot open %s" % path)
        super().__init__(h)
        self._path = path
        self._capacity = int(capacity_bytes)

    def reset(self):
        """Restart the stream (prefetch threads cannot rewind — close
        and reopen, like the reference prefetcher's BeforeFirst)."""
        self.close()
        h = self._lib.mxtpu_prefetch_open(os.fsencode(self._path),
                                          self._capacity)
        if not h:
            raise IOError("cannot reopen %s" % self._path)
        self._h = h
