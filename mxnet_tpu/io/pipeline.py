"""Staged asynchronous input pipeline: multi-worker decode + device
prefetch, so ``data_wait`` disappears from the step critical path.

The reference framework hides input cost behind compute with a whole
C++ iterator stack — PrefetcherIter → ThreadedIter → BatchLoader
(SURVEY §3.5) — whose Python port here had shrunk to one daemon thread
handing back *host* batches: decode was serial and the host→device
transfer still ran inside the consumer's step. Following the staged-
parallelism design of tf.data (Murray et al., VLDB 2021) and the
compute/transfer-overlap argument of PyTorch DDP (Li et al., VLDB
2020), this module splits the input path into three explicit stages:

1. **Decode/augment pool** — ``MXNET_DATA_WORKERS`` threads (numpy /
   cv2 / PIL release the GIL, the reference's OMP parser role). A
   single scheduler thread pulls work items from the source *in
   order* and fans the expensive decode out to the pool; because the
   resulting futures enter the hand-off queue in submission order,
   delivery order is always the source order — no reorder buffer,
   no nondeterminism. Sources that implement the split protocol
   (:meth:`DataIter.next_raw` + :meth:`DataIter.decode_raw`, see
   ``NDArrayIter``/``ImageRecordIter``) get true multi-worker decode;
   any other iterator degrades to serialized ``next()`` calls — still
   fully asynchronous with the consumer, like the old prefetcher.
2. **Device prefetch** — a placer thread calls ``jax.device_put`` on
   the next ``prefetch_depth`` batches (against the consumer's device
   or ``Sharding`` when a mesh / data-parallel placement is active)
   and *blocks until the transfer lands*, so H2D overlaps the current
   step's compute and the consumer receives device-resident arrays.
   Bytes and latency are accounted per array name under the telemetry
   ``h2d`` kind (``tools.diagnose`` renders an H2D table showing how
   much transfer ran off the critical path).
3. **Backpressure-bounded buffering** — every queue is bounded
   (decode: workers+depth futures; ready: ``prefetch_depth``), every
   put is stop-aware (timeout loop checking the stop event), and
   shutdown drains queues before joining, so ``reset()``/``close()``/
   GC never leak a blocked thread.

Donation safety: the fused train step (``fused_step.py``) donates only
weights and optimizer state — batch inputs ride in the non-donated
argument block — and each emitted batch is a fresh ``device_put``
result, never an alias of a buffer a previous step handed to XLA, so
pipeline batches feed ``fused_step``'s traced inputs directly.

Telemetry: the consumer-side ``data_wait`` span opens ONLY when the
ready queue runs dry (a non-blocking get is tried first), so the phase
now measures true input stalls instead of every fetch; all pipeline
threads are off the accounting thread, so their decode/transfer time
never pollutes the step timeline.
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

from .. import envs
from .io import DataBatch, DataIter

__all__ = ["AsyncInputPipeline", "data_workers", "pipeline_enabled",
           "placement_for_module", "make_sharded_pipeline",
           "place_batch", "stop_aware_put"]

_SENTINEL = object()      # end-of-epoch marker
_PUT_TICK = 0.05          # stop-aware put poll interval (seconds)


def stop_aware_put(q, item, stop, tick=_PUT_TICK):
    """Bounded put that gives up when ``stop`` fires, so a full queue
    can never wedge a producer thread past shutdown. Returns False
    when the put was abandoned. The one copy of the discipline every
    off-critical-path background stage uses (this pipeline's decode/
    placer threads; ``checkpoint.py``'s writer keeps the plain
    blocking put because its queue-full state IS the intended
    backpressure on the training thread)."""
    while not stop.is_set():
        try:
            q.put(item, timeout=tick)
            return True
        except queue.Full:
            continue
    return False


def data_workers(default=2):
    """The configured decode-pool width (``MXNET_DATA_WORKERS``)."""
    return max(1, envs.get_int("MXNET_DATA_WORKERS", default))


def pipeline_enabled():
    """The ``MXNET_DATA_PIPELINE`` gate for the fit-loop wiring —
    default ON; ``0``/``false``/``off`` fall back to the plain
    iterator (re-read each fit so benchmarks can toggle it)."""
    return envs.get_bool("MXNET_DATA_PIPELINE")


# ---------------------------------------------------------------------------
# device placement
# ---------------------------------------------------------------------------

def _placement_target(placement, name, data):
    """Resolve a placement spec to the device/sharding for one array.
    ``placement`` is a jax.Device, a Sharding, or a callable
    ``(name, array) -> device/sharding/None``."""
    if callable(placement) and not hasattr(placement, "device_kind") \
            and not hasattr(placement, "addressable_devices"):
        return placement(name, data)
    return placement


def _put_one(nd_arr, target, name):
    """Commit one NDArray to ``target`` and block until it is resident
    — on the placer thread, off the step critical path. When the array
    already sits where asked (``nd_array``'s async ``jnp.asarray``
    dispatched it to the default device), the block is still the
    transfer-completion barrier the consumer would otherwise pay
    inside its first op; either way the batch's bytes and the wait are
    accounted under h2d."""
    import time

    import jax

    from .. import telemetry
    from ..ndarray import NDArray
    if target is None or getattr(nd_arr, "stype", "default") != "default":
        return nd_arr            # sparse batches stay host-side
    data = getattr(nd_arr, "_data", None)
    if data is None:
        return nd_arr
    sharding = getattr(data, "sharding", None)
    resident = sharding == target or (
        getattr(target, "device_kind", None) is not None
        and getattr(data, "devices", None) is not None
        and data.devices() == {target})
    from .. import tracing
    t0 = time.perf_counter()
    out = nd_arr
    if not resident:
        data = jax.device_put(data, target)
        out = NDArray(data, ctx=nd_arr._ctx)
    data.block_until_ready()
    dur = time.perf_counter() - t0
    nbytes = int(getattr(data, "nbytes", 0) or 0)
    telemetry.h2d(name, nbytes, dur)
    if tracing._tracer is not None:
        # the placer runs AHEAD of consumption by design; the context
        # token parents the transfer to the step that was open while
        # it ran — explicit args, not thread identity (this thread is
        # off the accounting thread on purpose)
        args = tracing.context() or {}
        args["bytes"] = nbytes
        tracing.add("h2d:%s" % name, "io", t0, dur,
                    tid=tracing.track("io:h2d"), args=args)
    return out


def place_batch(batch, placement, data_names=None, label_names=None):
    """Place one batch's arrays on the target device/sharding.
    Handles :class:`DataBatch`, bare NDArrays, and (nested)
    lists/tuples of them — the gluon DataLoader's ``(data, label)``
    pairs included. Non-array leaves pass through untouched;
    ``data_names``/``label_names`` label the h2d accounting (the
    batch's own ``provide_data`` wins when set)."""
    from ..ndarray import NDArray
    if placement is None or batch is None:
        return batch
    if isinstance(batch, NDArray):
        name = data_names[0] if data_names else "data"
        return _put_one(batch, _placement_target(placement, name,
                                                 batch._data), name)
    if isinstance(batch, DataBatch):
        names_d = [d.name for d in batch.provide_data] \
            if batch.provide_data else list(data_names or [])
        names_l = [l.name for l in batch.provide_label] \
            if batch.provide_label else list(label_names or [])

        def put_roster(arrays, names, fallback):
            if arrays is None:
                return None
            out = []
            for i, a in enumerate(arrays):
                data = getattr(a, "_data", None)
                if not isinstance(a, NDArray) or data is None:
                    out.append(a)    # numpy/sparse leaves stay host-side
                    continue
                name = names[i] if i < len(names) else \
                    "%s%d" % (fallback, i)
                out.append(_put_one(a, _placement_target(
                    placement, name, data), name))
            return out

        placed = DataBatch(put_roster(batch.data, names_d, "data"),
                           put_roster(batch.label, names_l, "label"),
                           pad=batch.pad, index=batch.index,
                           bucket_key=batch.bucket_key,
                           provide_data=batch.provide_data,
                           provide_label=batch.provide_label)
        # bucketed batches (bucketing.BucketedPipeline) ride validity
        # info as attributes — the mask contract must survive placement
        for extra in ("valid_lengths", "valid_rows"):
            if hasattr(batch, extra):
                setattr(placed, extra, getattr(batch, extra))
        return placed
    if isinstance(batch, (list, tuple)):
        # a 2-element batch is the (data, label) convention — label the
        # second element's h2d accounting accordingly
        names_per = [data_names] * len(batch)
        if len(batch) == 2:
            names_per[1] = label_names or ["label"]
        placed = [place_batch(b, placement, names_per[i], label_names)
                  for i, b in enumerate(batch)]
        if hasattr(batch, "_fields"):    # namedtuple: positional fields
            return type(batch)(*placed)
        return type(batch)(placed)
    return batch


def _dp_placement(mesh, rep, shard, batch_args=None):
    """The one copy of ``Executor._dp_place``'s sharding rule as a
    placement callable: batch args whose leading dim splits over the
    mesh's device count go on ``shard``, everything else on ``rep`` —
    so batches the pipeline pre-places make the executor's own
    placement pass a no-op."""
    n_dp = mesh.devices.size

    def place(name, arr):
        if (batch_args is None or name in batch_args) \
                and getattr(arr, "ndim", 0) >= 1 \
                and arr.shape[0] % n_dp == 0:
            return shard
        return rep
    return place


def placement_for_module(module):
    """The placement spec matching a bound Module's executor: the
    mesh's dp/replicated shardings when the bind spans devices, else
    the single bound device. None when the module has no executor to
    consult."""
    ex = getattr(module, "_exec", None)
    if ex is None:
        return None
    mesh = getattr(ex, "_mesh", None)
    if mesh is not None:
        rep, shard = ex._dp_shardings()
        batch_args = set(getattr(ex, "_batch_args", ()) or ())
        return _dp_placement(mesh, rep, shard, batch_args)
    try:
        return ex._ctx.jax_device()
    except Exception:
        return None


def make_sharded_pipeline(source, mesh, prefetch_depth=2,
                         num_workers=None):
    """A pipeline whose batches land pre-sharded for a data-parallel
    mesh step: batch-dim-divisible arrays over ``dp``, the rest
    replicated (``parallel/data_parallel.py`` consumes these without a
    second ``device_put``)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    place = _dp_placement(mesh, NamedSharding(mesh, P()),
                          NamedSharding(mesh, P("dp")))
    return AsyncInputPipeline(source, num_workers=num_workers,
                              prefetch_depth=prefetch_depth,
                              placement=place)


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------

class AsyncInputPipeline(DataIter):
    """Three-stage asynchronous wrapper around a :class:`DataIter`
    (or anything with ``next()``/``reset()``).

    Stage 1 parallelizes decode across ``num_workers`` threads when the
    source implements the split protocol (``next_raw``/``decode_raw``),
    preserving source order; stage 2 moves each decoded batch onto
    ``placement`` (device / Sharding / per-array callable) ahead of
    consumption; stage 3 is the bounded, stop-aware buffering between
    them. Epoch semantics match ``PrefetchingIter``: the source's
    ``StopIteration`` ends the epoch, ``reset()`` restarts cleanly.
    """

    def __init__(self, source, num_workers=None, prefetch_depth=2,
                 placement=None):
        super().__init__(getattr(source, "batch_size", 0) or 0)
        self._source = source
        self._workers = num_workers if num_workers is not None \
            else data_workers()
        self._workers = max(1, int(self._workers))
        self.prefetch_depth = max(1, int(prefetch_depth))
        self._placement = placement
        self._split = hasattr(source, "next_raw") and \
            hasattr(source, "decode_raw")
        try:
            self._data_names = [d.name if hasattr(d, "name") else d[0]
                                for d in source.provide_data]
        except Exception:
            self._data_names = []
        try:
            self._label_names = [l.name if hasattr(l, "name") else l[0]
                                 for l in source.provide_label]
        except Exception:
            self._label_names = []
        self._stop = None
        self._threads = []
        self._pool = None
        self._decode_q = None
        self._ready_q = None
        self._exhausted = False
        self._start()

    # -- DataIter surface --------------------------------------------------
    @property
    def provide_data(self):
        return self._source.provide_data

    @property
    def provide_label(self):
        return self._source.provide_label

    def set_placement(self, placement):
        """Adopt a new device/sharding target. Takes effect on the next
        batch the placer touches (attribute reads are atomic); batches
        already in the ready queue keep their old placement — consumers
        transfer those themselves, exactly as before placement existed."""
        self._placement = placement

    # -- lifecycle ---------------------------------------------------------
    def _start(self):
        self._stop = threading.Event()
        self._exhausted = False
        # decode_q holds futures (split mode) or whole batches; its
        # bound is the in-flight decode window — workers + a margin so
        # the pool never idles waiting on the placer
        self._decode_q = queue.Queue(
            maxsize=self._workers + self.prefetch_depth)
        self._ready_q = queue.Queue(maxsize=self.prefetch_depth)
        if self._split and self._workers > 1:
            self._pool = ThreadPoolExecutor(
                max_workers=self._workers,
                thread_name_prefix="mxio-decode")
        else:
            self._pool = None
        sched = threading.Thread(target=self._scheduler, daemon=True,
                                 name="mxio-sched")
        placer = threading.Thread(target=self._placer, daemon=True,
                                  name="mxio-place")
        self._threads = [sched, placer]
        sched.start()
        placer.start()

    def _stop_aware_put(self, q, item):
        return stop_aware_put(q, item, self._stop)

    def _scheduler(self):
        """Stage-1 driver: pull work from the source IN ORDER (the
        source itself is never touched concurrently), fan decode out to
        the pool, and emit futures/batches in submission order."""
        from .. import tracing
        stop = self._stop
        src = self._source
        try:
            while not stop.is_set():
                tracing_on = tracing._tracer is not None
                try:
                    if self._pool is not None:
                        raw = src.next_raw()
                        if tracing_on:
                            # context captured HERE (the scheduling
                            # thread) and handed to the pool worker as
                            # an explicit token — the decode span is
                            # parented to the step that triggered the
                            # fetch, never to the worker thread
                            item = self._pool.submit(
                                self._decode_traced, raw,
                                tracing.context())
                        else:
                            item = self._pool.submit(src.decode_raw,
                                                     raw)
                    elif self._split:
                        # one worker: still use the split so randomness
                        # is drawn serially (bit-identical to eager)
                        if tracing_on:
                            item = self._decode_traced(
                                src.next_raw(), tracing.context())
                        else:
                            item = src.decode_raw(src.next_raw())
                    else:
                        item = src.next()
                except StopIteration:
                    break
                except Exception as exc:        # surface in consumer
                    self._stop_aware_put(self._decode_q, exc)
                    return
                if not self._stop_aware_put(self._decode_q, item):
                    return
        finally:
            self._stop_aware_put(self._decode_q, _SENTINEL)

    def _decode_traced(self, raw, ctx):
        """Decode one work item with its trace span, parented to the
        triggering step via the explicitly-propagated ``ctx`` token."""
        import time as _time

        from .. import tracing
        t0 = _time.perf_counter()
        out = self._source.decode_raw(raw)
        tracing.add("decode", "io", t0, _time.perf_counter() - t0,
                    tid=tracing.track("io:decode"), args=ctx)
        return out

    def _placer(self):
        """Stage-2 driver: resolve decode results in order, commit them
        to the target device/sharding (blocking HERE, off the critical
        path, so the consumer receives transfer-complete batches), and
        fill the bounded ready queue."""
        stop = self._stop
        while not stop.is_set():
            try:
                item = self._decode_q.get(timeout=_PUT_TICK)
            except queue.Empty:
                continue
            if item is _SENTINEL:
                self._stop_aware_put(self._ready_q, _SENTINEL)
                return
            if isinstance(item, Exception):
                self._stop_aware_put(self._ready_q, item)
                stop.set()       # the scheduler must not keep decoding
                return
            try:
                batch = item.result() if hasattr(item, "result") \
                    else item
                batch = place_batch(batch, self._placement,
                                    self._data_names,
                                    self._label_names)
            except Exception as exc:            # noqa: BLE001
                self._stop_aware_put(self._ready_q, exc)
                stop.set()       # the scheduler must not keep decoding
                return
            if not self._stop_aware_put(self._ready_q, batch):
                return

    def _shutdown_threads(self):
        """Stop, drain, then join — in that order. Draining both
        queues unblocks any producer mid-put; the stop-aware puts
        guarantee a bounded exit even if the consumer never drains.
        Returns the threads (if any) still alive after the join
        timeout — wedged inside a stalled source read/decode."""
        stop = self._stop
        if stop is None:
            return []
        stop.set()
        for q in (self._decode_q, self._ready_q):
            if q is None:
                continue
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
        for t in self._threads:
            t.join(timeout=5)
        wedged = [t for t in self._threads if t.is_alive()]
        self._threads = []
        if self._pool is not None:
            # a wedged producer may be stalled inside a pool decode:
            # don't let shutdown() block on it too
            self._pool.shutdown(wait=not wedged)
            self._pool = None
        return wedged

    def reset(self):
        """Stop the pipeline, reset the source, and restart with the
        SAME configured ``prefetch_depth`` and worker pool. Refuses to
        reset the source while a producer is wedged inside it (a
        stalled read) — resetting under a live reader would corrupt
        its cursor/record state."""
        wedged = self._shutdown_threads()
        if wedged:
            from ..base import MXNetError
            raise MXNetError(
                "input pipeline reset: producer thread(s) %s did not "
                "exit within the join timeout (source read stalled?); "
                "refusing to reset the source under a live reader"
                % [t.name for t in wedged])
        self._source.reset()
        self._start()

    def close(self):
        """Tear the pipeline down for good (also runs at GC). The
        source is the caller's — its own close()/GC handles it."""
        self._shutdown_threads()

    def __del__(self):
        try:
            self._shutdown_threads()
        except Exception:       # interpreter teardown
            pass

    # -- consumption -------------------------------------------------------
    def next(self):
        if self._exhausted:
            raise StopIteration
        try:
            # fast path: a ready batch means NO data stall — data_wait
            # must measure only true queue-dry time
            item = self._ready_q.get_nowait()
        except queue.Empty:
            from .. import telemetry
            with telemetry.span("data_wait"):
                item = self._blocking_get()
        if item is _SENTINEL:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, Exception):
            self._exhausted = True
            raise item
        return item

    def _blocking_get(self):
        stop = self._stop
        while True:
            try:
                return self._ready_q.get(timeout=_PUT_TICK)
            except queue.Empty:
                if stop.is_set():
                    return _SENTINEL
                if not any(t.is_alive() for t in self._threads):
                    # producers died without a sentinel (should not
                    # happen; defensive against a hard thread kill)
                    return _SENTINEL

    def iter_next(self):
        try:
            self._cached = self.next()
            return True
        except StopIteration:
            self._cached = None
            return False

    # the base-class protocol (iter_next + accessors) serves the batch
    # iter_next fetched
    def getdata(self):
        return self._cached.data

    def getlabel(self):
        return self._cached.label

    def getpad(self):
        return self._cached.pad

    def getindex(self):
        return self._cached.index
