"""IO namespace (parity: python/mxnet/io/)."""
from .io import (DataDesc, DataBatch, DataIter, ResizeIter, PrefetchingIter,
                 NDArrayIter, MNISTIter, CSVIter, LibSVMIter)
from .image_record import ImageRecordIter, ImageDetRecordIter
from .pipeline import (AsyncInputPipeline, data_workers, pipeline_enabled,
                       placement_for_module, make_sharded_pipeline,
                       place_batch)
