"""IO namespace (parity: python/mxnet/io/)."""
from .io import (DataDesc, DataBatch, DataIter, ResizeIter, PrefetchingIter,
                 NDArrayIter, MNISTIter, CSVIter, LibSVMIter)
from .image_record import ImageRecordIter, ImageDetRecordIter
