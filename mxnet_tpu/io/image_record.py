"""ImageRecordIter — the flagship image input path (reference:
src/io/iter_image_recordio_2.cc:748 + PrefetcherIter/BatchLoader
layering, SURVEY §3.5).

Design: one reader walks the .rec file (keyed by the .idx sidecar when
present), a thread pool decodes + augments images ahead of the
consumer (cv2/PIL release the GIL during JPEG decode — the role of the
reference's OMP parser threads), and whole batches land as NDArrays.
Augmentations cover the training-relevant core of
image_aug_default.cc: resize-shorter-edge, random/center crop, random
mirror, mean/std normalization.
"""
from __future__ import annotations

import concurrent.futures
import logging
import os
import threading

import numpy as np

from ..base import MXNetError
from ..recordio import MXRecordIO, MXIndexedRecordIO, unpack
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageRecordIter", "ImageDetRecordIter"]


def _decode_jpeg(payload):
    try:
        import cv2
        img = cv2.imdecode(np.frombuffer(payload, np.uint8),
                           cv2.IMREAD_COLOR)
        return img[:, :, ::-1]                  # BGR → RGB
    except ImportError:
        pass
    import io as _io
    from PIL import Image
    return np.asarray(Image.open(_io.BytesIO(payload)).convert("RGB"))


def _resize_shorter(img, size):
    import math
    h, w = img.shape[:2]
    if min(h, w) == size:
        return img
    if h < w:
        nh, nw = size, max(1, int(round(w * size / h)))
    else:
        nh, nw = max(1, int(round(h * size / w))), size
    try:
        import cv2
        return cv2.resize(img, (nw, nh), interpolation=cv2.INTER_LINEAR)
    except ImportError:
        from PIL import Image
        return np.asarray(Image.fromarray(img).resize((nw, nh)))


class ImageRecordIter(DataIter):
    """Batched, augmented iteration over an image RecordIO file
    (reference: ImageRecordIter, iter_image_recordio_2.cc:748)."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, label_width=1, shuffle=False,
                 rand_crop=False, rand_mirror=False, resize=-1,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, scale=1.0,
                 preprocess_threads=4, prefetch_buffer=4, seed=0,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        if len(data_shape) != 3:
            raise MXNetError(
                "ImageRecordIter data_shape must be (C, H, W), got %s"
                % (data_shape,))
        self._shape = tuple(int(s) for s in data_shape)
        self._label_width = int(label_width)
        self._shuffle = shuffle
        self._rand_crop = rand_crop
        self._rand_mirror = rand_mirror
        self._resize = int(resize)
        self._mean = np.asarray([mean_r, mean_g, mean_b], np.float32)
        self._std = np.asarray([std_r, std_g, std_b], np.float32)
        self._scale = float(scale)
        self._rng = np.random.RandomState(seed)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, int(preprocess_threads)),
            thread_name_prefix="imgrec")
        self._depth = max(1, int(prefetch_buffer))

        if path_imgidx and os.path.exists(path_imgidx):
            self._rec = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            self._keys = list(self._rec.keys)
        else:
            if shuffle:
                raise MXNetError(
                    "ImageRecordIter(shuffle=True) needs the .idx "
                    "sidecar (pass path_imgidx; im2rec writes one) — "
                    "sequential .rec scans cannot be shuffled")
            from . import native as _native
            if _native.available():
                # C++ prefetch thread stays ahead of decode (the
                # reference's PrefetcherIter, iter_prefetcher.h:47)
                self._rec = _native.PrefetchingRecordReader(path_imgrec)
            else:
                self._rec = MXRecordIO(path_imgrec, "r")
            self._keys = None           # sequential-scan mode
        self._lock = threading.Lock()   # serializes record reads

        c, h, w = self._shape
        self.provide_data = [DataDesc(data_name,
                                      (batch_size, c, h, w))]
        lshape = (batch_size,) if self._label_width == 1 \
            else (batch_size, self._label_width)
        self.provide_label = [DataDesc(label_name, lshape)]
        self.reset()

    # -- record access ----------------------------------------------------
    def _read_raw(self, key):
        with self._lock:
            if key is None:
                return self._rec.read()
            return self._rec.read_idx(key)

    def _epoch_keys(self):
        if self._keys is None:
            return None
        order = list(self._keys)
        if self._shuffle:
            self._rng.shuffle(order)
        return order

    # -- decode + augment -------------------------------------------------
    def _prepare_image(self, payload, mirror, crop_pos):
        """Decode + augment one record; returns (chw, header, geometry)
        where geometry = (oy, ox, th, tw, h, w, mirrored) describes the
        crop so subclasses can transform coordinates accordingly."""
        header, body = unpack(payload)
        img = _decode_jpeg(body).astype(np.float32)
        c, th, tw = self._shape
        if self._resize > 0:
            img = _resize_shorter(img.astype(np.uint8),
                                  self._resize).astype(np.float32)
        h, w = img.shape[:2]
        if h < th or w < tw:
            img = _resize_shorter(img.astype(np.uint8),
                                  max(th, tw)).astype(np.float32)
            h, w = img.shape[:2]
        if self._rand_crop:
            oy = int(crop_pos[0] * (h - th))
            ox = int(crop_pos[1] * (w - tw))
        else:
            oy, ox = (h - th) // 2, (w - tw) // 2
        img = img[oy:oy + th, ox:ox + tw]
        if mirror:
            img = img[:, ::-1]
        img = (img - self._mean) / self._std * self._scale
        chw = np.transpose(img, (2, 0, 1))
        return chw, header, (oy, ox, th, tw, h, w, bool(mirror))

    def _prepare(self, payload, mirror, crop_pos):
        chw, header, _ = self._prepare_image(payload, mirror, crop_pos)
        label = np.asarray(header.label, np.float32).reshape(-1)
        if label.size == 0:
            label = np.zeros((self._label_width,), np.float32)
        return chw, label[:self._label_width]

    def _draw(self, n):
        """The batch's augmentation randomness — drawn SERIALLY (from
        ``next_raw`` on the pipeline's scheduler thread, or inline on
        the eager path) so pooled decode is bit-identical to eager for
        the same seed, in the same batch order."""
        mirrors = self._rng.rand(n) < 0.5 \
            if self._rand_mirror else [False] * n
        crops = self._rng.rand(n, 2)
        return mirrors, crops

    def _assemble(self, payloads):
        mirrors, crops = self._draw(len(payloads))
        return self._assemble_drawn(payloads, mirrors, crops)

    def _assemble_drawn(self, payloads, mirrors, crops):
        futures = [self._pool.submit(self._prepare, p, m, cp)
                   for p, m, cp in zip(payloads, mirrors, crops)]
        images, labels = zip(*[f.result() for f in futures])
        from ..ndarray import array as nd_array
        data = nd_array(np.stack(images))
        lab = np.stack(labels)
        if self._label_width == 1 and lab.ndim == 2:
            lab = lab[:, 0]
        return DataBatch([data], [nd_array(lab)], pad=0)

    def _next_payloads(self):
        """Serialized record IO for one batch: raw (still-encoded)
        payloads + the pad count, or StopIteration at epoch end."""
        bs = self.batch_size
        if self._order is not None:
            if self._cursor >= len(self._order):
                raise StopIteration
            keys = self._order[self._cursor:self._cursor + bs]
            self._cursor += bs
            pad = bs - len(keys)
            if pad:
                # round_batch semantics: wrap to the epoch start (cycling
                # if the dataset is smaller than one batch) and report
                # the pad count so score()/metrics can mask
                keys = keys + [self._order[i % len(self._order)]
                               for i in range(pad)]
            payloads = []
            for k in keys:
                raw = self._read_raw(k)
                if raw is None:
                    raise StopIteration
                payloads.append(raw)
            return payloads, pad
        # sequential scan: read up to bs records, pad from this batch
        payloads = []
        for _ in range(bs):
            raw = self._read_raw(None)
            if raw is None:
                break
            payloads.append(raw)
        if not payloads:
            raise StopIteration
        pad = bs - len(payloads)
        if pad:
            reps = [payloads[i % len(payloads)] for i in range(pad)]
            payloads = payloads + reps
        return payloads, pad

    # -- DataIter protocol ------------------------------------------------
    def reset(self):
        self._order = self._epoch_keys()
        self._cursor = 0
        if self._keys is None:
            self._rec.reset()

    # split protocol (io/pipeline.py): record IO + rng draws serialize
    # in next_raw; the expensive JPEG decode/augment parallelizes in
    # decode_raw across the pipeline's workers (each of which may also
    # fan single images out to this iterator's own thread pool)
    def next_raw(self):
        payloads, pad = self._next_payloads()
        mirrors, crops = self._draw(len(payloads))
        return payloads, mirrors, crops, pad

    def decode_raw(self, raw):
        payloads, mirrors, crops, pad = raw
        batch = self._assemble_drawn(payloads, mirrors, crops)
        batch.pad = pad
        return batch

    def next(self):
        return self.decode_raw(self.next_raw())

    def close(self):
        """Shut the decode pool and the record reader down."""
        pool = getattr(self, "_pool", None)
        if pool is not None:
            pool.shutdown(wait=False)
            self._pool = None
        rec = getattr(self, "_rec", None)
        if rec is not None:
            rec.close()
            self._rec = None

    def __del__(self):
        self.close()


class ImageDetRecordIter(ImageRecordIter):
    """Detection variant (reference: src/io/iter_image_det_recordio.cc):
    each record's label is a variable-length flat vector of
    ``object_width``-wide object rows ([cls, x1, y1, x2, y2, ...]);
    batches pad every image to ``label_pad_width`` objects with
    ``label_pad_value`` so the label tensor is rectangular —
    (batch, label_pad_width, object_width)."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 object_width=5, label_pad_width=16,
                 label_pad_value=-1.0, **kwargs):
        self._object_width = int(object_width)
        self._label_pad_width = int(label_pad_width)
        self._label_pad_value = float(label_pad_value)
        kwargs.setdefault("label_width", 1)
        super().__init__(path_imgrec, data_shape, batch_size, **kwargs)
        self.provide_label = [DataDesc(
            self.provide_label[0].name,
            (self.batch_size, self._label_pad_width, self._object_width))]

    def _transform_boxes(self, objs, geom):
        """Map normalized [x1,y1,x2,y2] from the original image into
        the cropped/mirrored frame (reference:
        image_det_aug_default.cc); boxes left entirely outside the crop
        become padding rows."""
        oy, ox, th, tw, h, w, mirrored = geom
        out = objs.copy()
        x1 = objs[:, 1] * w - ox
        y1 = objs[:, 2] * h - oy
        x2 = objs[:, 3] * w - ox
        y2 = objs[:, 4] * h - oy
        nx1 = np.clip(x1 / tw, 0.0, 1.0)
        ny1 = np.clip(y1 / th, 0.0, 1.0)
        nx2 = np.clip(x2 / tw, 0.0, 1.0)
        ny2 = np.clip(y2 / th, 0.0, 1.0)
        if mirrored:
            nx1, nx2 = 1.0 - nx2, 1.0 - nx1
        out[:, 1], out[:, 2], out[:, 3], out[:, 4] = nx1, ny1, nx2, ny2
        gone = (nx2 - nx1 <= 0) | (ny2 - ny1 <= 0)
        out[gone] = self._label_pad_value
        return out

    def _prepare(self, payload, mirror, crop_pos):
        img, header, geom = self._prepare_image(payload, mirror,
                                                crop_pos)
        flat = np.asarray(header.label, np.float32).reshape(-1)
        ow, pw = self._object_width, self._label_pad_width
        if flat.size % ow:
            raise MXNetError(
                "detection record label length %d is not a multiple of "
                "object_width %d" % (flat.size, ow))
        n = flat.size // ow
        if n > pw:
            raise MXNetError(
                "record has %d objects but label_pad_width is %d; "
                "raise label_pad_width" % (n, pw))
        objs = np.full((pw, ow), self._label_pad_value, np.float32)
        if n:
            objs[:n] = self._transform_boxes(flat.reshape(n, ow), geom)
        return img, objs

