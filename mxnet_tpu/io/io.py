"""Data iterators (parity: python/mxnet/io/io.py + src/io/).

The reference's C++ iterator stack (PrefetcherIter → BatchLoader →
parser, SURVEY §3.5) maps to: python iterators + a threaded
``PrefetchingIter`` (the dmlc::ThreadedIter role). Decode/augment
parallelism belongs to the host CPU either way — on TPU the goal is
keeping the input pipeline off the device critical path, which the
prefetcher provides.
"""
from __future__ import annotations

import gzip
import os
import struct
from collections import namedtuple, OrderedDict

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray, array as nd_array
from ..context import cpu
__all__ = ["DataDesc", "DataBatch", "DataIter", "ResizeIter",
           "PrefetchingIter", "NDArrayIter", "MNISTIter", "CSVIter",
           "LibSVMIter"]


def _data_wait_span():
    """Telemetry data-wait phase for iterator fetches. Same-phase
    nesting is counted once, so `fit`'s own outer data_wait span and
    these inner ones never double count (README "Observability")."""
    from .. import telemetry
    return telemetry.span("data_wait")


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Data description incl. dtype/layout (reference: io/io.py:57)."""

    def __new__(cls, name, shape, dtype=np.float32, layout='NCHW'):
        ret = super().__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find('N')


class DataBatch:
    """One batch (reference: io/io.py:146)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), "Data must be list of NDArrays"
        if label is not None:
            assert isinstance(label, (list, tuple)), "Label must be list of NDArrays"
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes)


class DataIter:
    """Base iterator (reference: io/io.py:211).

    Iterators that want multi-worker decode under the async input
    pipeline (``io/pipeline.py``) additionally implement the *split
    protocol*: ``next_raw()`` — the cheap, serialized part (record IO,
    cursor math, randomness draws) returning an opaque work item — and
    ``decode_raw(raw)`` — the expensive, thread-safe part returning the
    finished :class:`DataBatch`. ``next()`` must stay equivalent to
    ``decode_raw(next_raw())`` so the pooled path is bit-identical to
    the eager one."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        with _data_wait_span():
            if self.iter_next():
                return DataBatch(data=self.getdata(),
                                 label=self.getlabel(),
                                 pad=self.getpad(),
                                 index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        pass

    def getdata(self):
        pass

    def getlabel(self):
        pass

    def getindex(self):
        return None

    def getpad(self):
        pass


class ResizeIter(DataIter):
    """Resize over/under-sized iterators (reference: io/io.py:299)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, 'default_bucket_key'):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class _CombinedSource(DataIter):
    """The multi-iterator merge the old prefetch worker performed
    inline: one ``next()`` pulls a batch from EVERY child and
    concatenates data/label rosters (first exhausted child ends the
    epoch, as before)."""

    def __init__(self, iters):
        super().__init__(getattr(iters[0], "batch_size", 0) or 0)
        self.iters = iters

    def next(self):
        batches = [i.next() for i in self.iters]
        return DataBatch(
            data=sum([b.data for b in batches], []),
            label=sum([(b.label or []) for b in batches], []),
            pad=max(b.pad or 0 for b in batches))

    def reset(self):
        for i in self.iters:
            i.reset()

    @property
    def provide_data(self):
        return sum([i.provide_data for i in self.iters], [])

    @property
    def provide_label(self):
        return sum([i.provide_label for i in self.iters], [])


class PrefetchingIter(DataIter):
    """Background prefetcher (the dmlc::ThreadedIter / PrefetcherIter
    role, reference: io/io.py:355 + iter_prefetcher.h) — now a thin
    wrapper over the staged :class:`~mxnet_tpu.io.pipeline.
    AsyncInputPipeline`: a multi-worker decode pool
    (``MXNET_DATA_WORKERS``; order-preserving) replaces the old single
    worker loop, an optional ``placement`` (device / Sharding /
    per-array callable) moves batches onto the consumer's device ahead
    of time, ``reset()`` honors the configured ``prefetch_depth``, and
    shutdown is drain-then-join with stop-aware puts — no leaked or
    wedged threads."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2, num_workers=None, placement=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.prefetch_depth = max(1, int(prefetch_depth))
        from .pipeline import AsyncInputPipeline
        source = iters[0] if self.n_iter == 1 else _CombinedSource(iters)
        self._pipeline = AsyncInputPipeline(
            source, num_workers=num_workers,
            prefetch_depth=self.prefetch_depth, placement=placement)

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(r[x[0]], x[1])
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(r[x[0]], x[1])
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def set_placement(self, placement):
        """Adopt a device/sharding target for batches placed from now
        on (fit calls this when it knows the bound executor's
        placement); in-flight host batches still work — the executor
        transfers them itself like before."""
        self._pipeline.set_placement(placement)

    def reset(self):
        # delegates: stop + drain + join, reset children, restart with
        # the CONFIGURED depth (the old code rebuilt maxsize=2 here)
        self._pipeline.reset()

    def close(self):
        pipeline = getattr(self, "_pipeline", None)
        if pipeline is not None:
            pipeline.close()

    def __del__(self):
        try:
            self.close()
        except Exception:       # interpreter teardown
            pass

    def next(self):
        # the pipeline's queue get is the consumer-visible data wait —
        # and it opens a data_wait span only when the queue runs dry
        return self._pipeline.next()

    # the iter_next/getdata protocol delegates to the pipeline's
    # cached-batch implementation
    def iter_next(self):
        return self._pipeline.iter_next()

    def getdata(self):
        return self._pipeline.getdata()

    def getlabel(self):
        return self._pipeline.getlabel()

    def getpad(self):
        return self._pipeline.getpad()

    def getindex(self):
        return self._pipeline.getindex()


def _as_host_view(v):
    """A host numpy view of one source array WITHOUT copying when the
    buffer already lives in host memory: plain numpy passes through
    ``np.asarray`` (no copy), and an NDArray on a host backend is
    exported zero-copy through DLPack (read-only — the iterator only
    ever gathers from it). Device-resident NDArrays (or anything DLPack
    refuses) fall back to the old ``asnumpy()`` copy."""
    if isinstance(v, NDArray):
        try:
            return np.from_dlpack(v._data)
        except Exception:
            return v.asnumpy()
    return np.asarray(v)


def _init_data(data, allow_empty, default_name):
    """Normalize data into list of (name, numpy) (reference: io/utils.py)."""
    assert (data is not None) or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = OrderedDict([(default_name, data[0])])
        else:
            data = OrderedDict(
                [('_%d_%s' % (i, default_name), d)
                 for i, d in enumerate(data)])
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    for k, v in data.items():
        if not isinstance(v, (np.ndarray, NDArray)):
            raise TypeError("Invalid type '%s' for %s, should be NDArray or "
                            "numpy.ndarray" % (type(v), k))
    return list(OrderedDict(
        [(k, _as_host_view(v)) for k, v in data.items()]).items())


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io/io.py:490)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle='pad', data_name='data',
                 label_name='softmax_label'):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self.num_data = self.idx.shape[0]
        if last_batch_handle == 'discard':
            self.num_data = (self.num_data // batch_size) * batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self.idx)
        if self.last_batch_handle == 'roll_over' and \
                -self.batch_size < self.cursor < 0:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) \
                % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        with _data_wait_span():
            return self.decode_raw(self.next_raw())

    # -- split protocol (async pipeline, io/pipeline.py) -----------------
    def next_raw(self):
        """Serialized half: advance the cursor (cheap index math) and
        hand the gather position to a decode worker."""
        if not self.iter_next():
            raise StopIteration
        return (self.cursor, self._pad_at(self.cursor))

    def decode_raw(self, raw):
        """Parallel half: gather + stack the batch at an explicit
        cursor — pure reads of the shared source arrays and the
        epoch-stable shuffle order, safe across decode workers."""
        cursor, pad = raw
        return DataBatch(data=self._getdata(self.data, cursor),
                         label=self._getdata(self.label, cursor),
                         pad=pad, index=None)

    def _getdata(self, data_source, cursor=None):
        cursor = self.cursor if cursor is None else cursor
        end = min(cursor + self.batch_size, self.num_data)
        s = slice(cursor, end)
        out = []
        for _, src in data_source:
            chunk = src[self.idx[s]]
            if chunk.shape[0] < self.batch_size:
                if self.last_batch_handle == 'pad':
                    pad = self.batch_size - chunk.shape[0]
                    chunk = np.concatenate(
                        [chunk, src[self.idx[:pad]]], axis=0)
            out.append(nd_array(chunk))
        return out

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def _pad_at(self, cursor):
        if self.last_batch_handle == 'pad' and \
                cursor + self.batch_size > self.num_data:
            return cursor + self.batch_size - self.num_data
        return 0

    def getpad(self):
        return self._pad_at(self.cursor)


def _read_idx_file(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, 'rb') as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(dims)


class MNISTIter(DataIter):
    """MNIST idx-format iterator (reference: src/io/iter_mnist.cc).

    Reads standard idx(.gz) files. ``flat`` yields (batch, 784);
    otherwise (batch, 1, 28, 28). Pixels scaled to [0,1) like the
    reference (iter_mnist.cc normalize).
    """

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, silent=False, seed=0,
                 input_shape=None, **kwargs):
        super().__init__(batch_size)
        for p in (image, label):
            if not os.path.exists(p) and not os.path.exists(p + ".gz"):
                raise MXNetError("MNISTIter: file not found: %s" % p)
        image = image if os.path.exists(image) else image + ".gz"
        label = label if os.path.exists(label) else label + ".gz"
        self._images = _read_idx_file(image).astype(np.float32) / 256.0
        self._labels = _read_idx_file(label).astype(np.float32)
        if flat:
            self._images = self._images.reshape(len(self._images), -1)
        else:
            self._images = self._images.reshape(len(self._images), 1,
                                                *self._images.shape[1:])
        self._shuffle = shuffle
        self._seed = seed
        self._inner = NDArrayIter(self._images, self._labels, batch_size,
                                  shuffle=shuffle,
                                  last_batch_handle='discard')

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


class CSVIter(DataIter):
    """CSV iterator (reference: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=',', dtype=np.float32,
                          ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=',', dtype=np.float32,
                               ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
        else:
            label = np.zeros((data.shape[0],), dtype=np.float32)
        self._inner = NDArrayIter(
            data, label, batch_size,
            last_batch_handle='roll_over' if round_batch else 'discard')
        self._inner.label = [( 'label', self._inner.label[0][1])]

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return [DataDesc('label', d.shape, d.dtype)
                for d in self._inner.provide_label]

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


class LibSVMIter(DataIter):
    """LibSVM-format iterator (reference: src/io/iter_libsvm.cc).

    Parses ``label idx:val ...`` lines into ONE scipy CSR matrix and
    yields CSRNDArray batches by slicing it — the sparse structure is
    never densified (the reference's iterator likewise stays CSR
    end-to-end). ``round_batch=True`` wraps the final short batch
    around to the beginning, like the reference's round_batch.
    """

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=None, batch_size=1, round_batch=True,
                 data_name='data', label_name='softmax_label', **kwargs):
        super().__init__(batch_size)
        import scipy.sparse as spsp
        feat_dim = int(np.prod(data_shape))

        def parse(fname, dim):
            vals, cols, indptr, heads = [], [], [0], []
            with open(fname) as f:
                for line in f:
                    parts = line.strip().split()
                    if not parts:
                        continue
                    heads.append(float(parts[0]))
                    for tok in parts[1:]:
                        i, v = tok.split(":")
                        cols.append(int(i))
                        vals.append(float(v))
                    indptr.append(len(cols))
            m = spsp.csr_matrix(
                (np.asarray(vals, np.float32),
                 np.asarray(cols, np.int64), np.asarray(indptr, np.int64)),
                shape=(len(indptr) - 1, dim))
            return m, np.asarray(heads, np.float32)

        self._csr, label = parse(data_libsvm, feat_dim)
        if label_libsvm is not None:
            lmat, _ = parse(label_libsvm, int(np.prod(label_shape)))
            label = lmat.toarray()
        self._label = label
        self._num = self._csr.shape[0]
        self._round = round_batch
        self._cursor = 0
        self._data_shape = tuple(data_shape)
        self._data_name = data_name
        self._label_name = label_name

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self._data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self._label_name,
                         (self.batch_size,) + self._label.shape[1:])]

    def reset(self):
        self._cursor = 0

    def iter_next(self):
        return self._cursor < self._num

    def next(self):
        if not self.iter_next():
            raise StopIteration
        from ..ndarray import sparse as _sp
        start = self._cursor
        stop = start + self.batch_size
        self._cursor = stop
        if stop <= self._num:
            idx = np.arange(start, stop)
            pad = 0
        elif self._round:
            idx = np.arange(start, stop) % self._num
            pad = 0
        else:
            idx = np.arange(start, self._num)
            pad = stop - self._num
            idx = np.concatenate([idx, np.zeros(pad, np.int64)])
        data = _sp.csr_matrix(self._csr[idx])
        label = nd_array(self._label[idx])
        return DataBatch(data=[data], label=[label], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
