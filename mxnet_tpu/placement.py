"""Model/operator placement: ``ctx_group`` / ``group2ctx`` → per-group
compiled segments with explicit cross-group activation transfer.

Parity: ``src/executor/graph_executor.cc:907`` (AssignContext) +
``python/mxnet/symbol/symbol.py:1369-1416`` (bind's group2ctx). The
reference assigns each ``AttrScope(ctx_group=...)`` subgraph to the
device named by ``group2ctx`` and inserts ``_CrossDeviceCopy`` nodes at
the boundaries. The TPU-native equivalent here partitions the bound
plan into contiguous same-group segments, compiles each segment as its
own XLA program pinned to the group's device (``jax.jit(device=...)``),
and performs the boundary activation transfer with ``jax.device_put``
— the copy the reference's special op did, made explicit. Training
chains ``jax.vjp`` segment by segment in reverse, moving cotangents to
each producer's device and accumulating argument gradients on the
device of the argument's first consumer.

This is deliberately NOT the single-fused-program path: operator
placement exists to split a too-big model across devices, which is a
multiple-program-multiple-device decision — the same trade the
reference makes when AssignContext severs its graph.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .base import MXNetError
from .context import Context

__all__ = ["GroupedProgram"]


class GroupedProgram:
    """Executes an Executor's plan as device-pinned segment programs."""

    def __init__(self, executor, group2ctx):
        self._ex = executor
        self._group2ctx = {}
        for g, c in (group2ctx or {}).items():
            if isinstance(c, (list, tuple)):
                # reference semantics allow a ctx list per group (one
                # copy per DP replica); single-replica placement takes
                # the first
                c = c[0]
            self._group2ctx[g] = c if isinstance(c, Context) else Context(c)
        self._build_segments()

    # -- plan partitioning ----------------------------------------------
    def _node_group(self, pi):
        node = self._ex._plan_nodes[pi]
        return node._extra_attrs.get("ctx_group")

    def _group_device(self, group):
        if group is None or group not in self._group2ctx:
            return self._ex._ctx.jax_device()
        return self._group2ctx[group].jax_device()

    def _build_segments(self):
        ex = self._ex
        plan = ex._plan
        segments: List[Dict[str, Any]] = []
        cur = None
        for pi in range(len(plan)):
            dev = self._group_device(self._node_group(pi))
            if cur is None or cur["dev"] is not dev:
                cur = {"dev": dev, "idxs": []}
                segments.append(cur)
            cur["idxs"].append(pi)
        # external references consumed by each segment
        for si, seg in enumerate(segments):
            inside = set(seg["idxs"])
            ext: List[tuple] = []
            seen = set()
            for pi in seg["idxs"]:
                _, _, bindings, rs, _, _ = plan[pi]
                for b in bindings:
                    key = None
                    if b[0] in ("arg", "aux"):
                        key = b
                    elif b[1] not in inside:
                        key = ("res", b[1], b[2])
                    if key is not None and key not in seen:
                        seen.add(key)
                        ext.append(key)
            seg["ext"] = ext
            seg["rng_slots"] = [plan[pi][3] for pi in seg["idxs"]
                                if plan[pi][3] is not None]
        self.segments = segments
        self._seg_fns: Dict[tuple, Any] = {}

    # -- segment program --------------------------------------------------
    def _segment_fn(self, si, is_train):
        """Jitted program of segment ``si``: (ext_vals, rngs) ->
        (per-node output tuples, aux updates)."""
        import jax
        key = (si, bool(is_train))
        fn = self._seg_fns.get(key)
        if fn is not None:
            return fn
        ex = self._ex
        plan = ex._plan
        seg = self.segments[si]
        idxs = list(seg["idxs"])
        ext = list(seg["ext"])
        ext_pos = {ref: i for i, ref in enumerate(ext)}
        rng_pos = {s: i for i, s in enumerate(seg["rng_slots"])}
        inside_pos = {pi: j for j, pi in enumerate(idxs)}

        def seg_run(ext_vals, rng_keys):
            from . import ops as _ops
            results = []
            aux_updates = []          # (aux_slot, value)
            for pi in idxs:
                op, nattrs, bindings, rs, aux_wb, slot = plan[pi]
                vals = []
                for b in bindings:
                    if b[0] in ("arg", "aux"):
                        vals.append(ext_vals[ext_pos[b]])
                    elif b[1] in inside_pos:
                        vals.append(results[inside_pos[b[1]]][b[2]])
                    else:
                        vals.append(ext_vals[ext_pos[("res", b[1], b[2])]])
                attrs = nattrs
                if "__train__" in op.defaults:
                    attrs = dict(nattrs, __train__=is_train)
                if rs is not None:
                    out = op.forward(attrs, *vals, rng=rng_keys[rng_pos[rs]])
                else:
                    out = op.forward(attrs, *vals)
                if not isinstance(out, (tuple, list)):
                    out = (out,)
                n_out = op.resolve_num_outputs(attrs)
                results.append(tuple(out[:n_out]))
                for wb, val in zip(aux_wb, out[n_out:]):
                    if wb is not None:
                        aux_updates.append((wb, val))
            return (tuple(results),
                    tuple(v for _, v in aux_updates))

        # record the aux-slot order once (static per segment)
        aux_slots = []
        for pi in idxs:
            op, nattrs, _, _, aux_wb, _ = plan[pi]
            for wb in aux_wb:
                if wb is not None:
                    aux_slots.append(wb)
        seg["aux_slots"] = aux_slots

        # placement comes from the committed inputs: _gather_ext puts
        # every external value (and forward/forward_backward the rng
        # keys) on the segment's device, so the compiled program runs
        # there — jit(device=...) is deprecated in this jax.
        # Staged through compile_watch so cross-group execution shows
        # up in compile telemetry; the cache token digests the
        # segment's op/attr/binding plan (the content this closure
        # bakes in), and the argument signature carries the device
        # placement, so persistent-cache entries cannot collide
        # across different groupings.
        import hashlib

        from . import compile_watch
        from .ops.registry import attr_key
        token = hashlib.sha256(repr(
            (key, [(plan[pi][0].name, attr_key(plan[pi][1]),
                    plan[pi][2:]) for pi in idxs],
             ext)).encode()).hexdigest()
        fn = compile_watch.jit(seg_run, "placement:seg%d" % si,
                               statics=token[:16], storm=False,
                               cache_token=token)
        self._seg_fns[key] = fn
        return fn

    # -- execution --------------------------------------------------------
    def _gather_ext(self, seg, arg_vals, aux_state, res_store):
        import jax
        vals = []
        for ref in seg["ext"]:
            if ref[0] == "arg":
                v = arg_vals[ref[1]]
            elif ref[0] == "aux":
                v = aux_state[ref[1]]
            else:
                v = res_store[(ref[1], ref[2])]
            # the cross-group activation/parameter transfer (the
            # reference's _CrossDeviceCopy, graph_executor.cc:907)
            vals.append(jax.device_put(v, seg["dev"]))
        return tuple(vals)

    def forward(self, arg_vals, aux_vals, rng_keys, is_train):
        ex = self._ex
        res_store: Dict[Tuple[int, int], Any] = {}
        aux_state = list(aux_vals)
        for si, seg in enumerate(self.segments):
            fn = self._segment_fn(si, is_train)
            ext = self._gather_ext(seg, arg_vals, aux_state, res_store)
            import jax
            rngs = tuple(jax.device_put(rng_keys[s], seg["dev"])
                         for s in seg["rng_slots"])
            results, aux_up = fn(ext, rngs)
            for j, pi in enumerate(seg["idxs"]):
                for oi, v in enumerate(results[j]):
                    res_store[(pi, oi)] = v
            for slot, v in zip(seg["aux_slots"], aux_up):
                aux_state[slot] = v
        outs = []
        for h in ex._head_refs:
            if h[0] == "arg":
                outs.append(arg_vals[h[1]])
            elif h[0] == "aux":
                outs.append(aux_state[h[1]])
            else:
                outs.append(res_store[(h[1], h[2])])
        return tuple(outs), tuple(aux_state)

    def forward_backward(self, arg_vals, aux_vals, rng_keys, out_grads):
        """Chained per-segment vjp: forward pass records one vjp per
        segment; the reverse sweep routes each segment's output
        cotangents (head grads + downstream consumers) back through it,
        transferring cotangents onto the producing segment's device."""
        import jax
        import jax.numpy as jnp
        ex = self._ex
        gpos = set(ex._grad_positions)
        res_store: Dict[Tuple[int, int], Any] = {}
        aux_state = list(aux_vals)
        vjps = []
        for si, seg in enumerate(self.segments):
            fn = self._segment_fn(si, is_train=True)
            ext = self._gather_ext(seg, arg_vals, aux_state, res_store)
            rngs = tuple(jax.device_put(rng_keys[s], seg["dev"])
                         for s in seg["rng_slots"])
            diff_mask = [ref[0] == "res"
                         or (ref[0] == "arg" and ref[1] in gpos)
                         for ref in seg["ext"]]
            diff_vals = tuple(v for v, m in zip(ext, diff_mask) if m)
            nondiff = tuple(v for v, m in zip(ext, diff_mask) if not m)

            def closed(diff_vals, _seg=seg, _fn=fn, _mask=tuple(diff_mask),
                       _nondiff=nondiff, _rngs=rngs):
                it_d = iter(diff_vals)
                it_n = iter(_nondiff)
                ext_vals = tuple(next(it_d) if m else next(it_n)
                                 for m in _mask)
                results, aux_up = _fn(ext_vals, _rngs)
                return results, aux_up

            (results, aux_up), vjp_fn = jax.vjp(closed, diff_vals)
            vjps.append((seg, diff_mask, vjp_fn, results, aux_up))
            for j, pi in enumerate(seg["idxs"]):
                for oi, v in enumerate(results[j]):
                    res_store[(pi, oi)] = v
            for slot, v in zip(seg["aux_slots"], aux_up):
                aux_state[slot] = v

        # head cotangents seed the reverse sweep
        cots: Dict[Tuple[int, int], Any] = {}

        def add_cot(key, val, dev):
            val = jax.device_put(val, dev)
            if key in cots:
                cots[key] = cots[key] + val
            else:
                cots[key] = val

        seg_of = {}
        for seg in self.segments:
            for pi in seg["idxs"]:
                seg_of[pi] = seg
        for h, og in zip(ex._head_refs, out_grads):
            if h[0] == "res":
                add_cot((h[1], h[2]), og, seg_of[h[1]]["dev"])

        arg_grads: Dict[int, Any] = {}
        outs = []
        for h in ex._head_refs:
            if h[0] == "arg":
                outs.append(arg_vals[h[1]])
            elif h[0] == "aux":
                outs.append(aux_state[h[1]])
            else:
                outs.append(res_store[(h[1], h[2])])

        for seg, diff_mask, vjp_fn, results, aux_up in reversed(vjps):
            out_cots = tuple(
                tuple(cots.get((pi, oi),
                               jnp.zeros(results[j][oi].shape,
                                         results[j][oi].dtype))
                      for oi in range(len(results[j])))
                for j, pi in enumerate(seg["idxs"]))
            aux_cots = tuple(jnp.zeros(v.shape, v.dtype) for v in aux_up)
            (diff_cots,) = vjp_fn((out_cots, aux_cots))
            it = iter(diff_cots)
            for ref, m in zip(seg["ext"], diff_mask):
                if not m:
                    continue
                c = next(it)
                if ref[0] == "arg":
                    p = ref[1]
                    if p in arg_grads:
                        arg_grads[p] = arg_grads[p] + jax.device_put(
                            c, arg_grads[p].sharding)
                    else:
                        arg_grads[p] = c
                else:
                    key = (ref[1], ref[2])
                    add_cot(key, c, seg_of[ref[1]]["dev"])

        grads = []
        for p in ex._grad_positions:
            if p in arg_grads:
                grads.append(arg_grads[p])
            else:
                a = arg_vals[p]
                grads.append(jnp.zeros(a.shape, a.dtype))
        return tuple(outs), tuple(aux_state), tuple(grads)
