"""Automatic symbol naming (parity with python/mxnet/name.py)."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]


class NameManager:
    """Assigns ``opname%d`` style names to anonymous symbols."""

    _current = threading.local()

    def __init__(self):
        self._counter = {}
        self._old_manager = None

    def get(self, name, hint):
        if name:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = '%s%d' % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        if not hasattr(NameManager._current, "value"):
            NameManager._current.value = NameManager()
        self._old_manager = NameManager._current.value
        NameManager._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_manager
        NameManager._current.value = self._old_manager

    @staticmethod
    def current():
        if not hasattr(NameManager._current, "value"):
            NameManager._current.value = NameManager()
        return NameManager._current.value


class Prefix(NameManager):
    """Prepends a prefix to every name (reference: name.py:74)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name
