"""Detection image pipeline: Det* augmenters + ImageDetIter.

Parity surface: python/mxnet/image/detection.py:39-624 (DetAugmenter
family, CreateDetAugmenter, ImageDetIter). Labels are (N, 5+) float
rows ``[cls, x1, y1, x2, y2, ...]`` with corner coordinates normalized
to [0, 1]; augmenters transform image AND boxes together, and objects
ejected by a crop become invalid rows (cls = -1). The box geometry is
pure numpy — decode/augment run host-side exactly as the reference's
OpenCV path does, keeping the TPU program free of ragged shapes; the
record-file variant (io.ImageDetRecordIter) shares the same
conventions.
"""
from __future__ import annotations

import json
import random as pyrandom

import numpy as np

from ..base import MXNetError
from .. import io as _io
from .. import ndarray as nd
from .image import (Augmenter, CreateAugmenter, ImageIter, imresize,
                    fixed_crop)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateMultiRandCropAugmenter", "CreateDetAugmenter",
           "ImageDetIter"]


def _as_np(img):
    return img.asnumpy() if isinstance(img, nd.NDArray) else np.asarray(img)


class DetAugmenter(object):
    """Detection augmenter base: ``__call__(src, label) -> (src, label)``
    (ref detection.py:39)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs.copy()
        for k, v in self._kwargs.items():
            if isinstance(v, np.ndarray):
                self._kwargs[k] = v.tolist()

    def dumps(self):
        """Name + init params, for iterator serialization."""
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a plain image augmenter whose transform keeps box geometry
    valid (color/cast/normalize) (ref detection.py:65)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise TypeError("DetBorrowAug requires an image Augmenter")
        super().__init__(augmenter=augmenter.__class__.__name__)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly run one of ``aug_list`` (or none, with ``skip_prob``)
    (ref detection.py:90)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or pyrandom.random() < self.skip_prob:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and x-coordinates with probability ``p``
    (ref detection.py:126)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() >= self.p:
            return src, label
        img = _as_np(src)[:, ::-1]
        out = np.array(label, np.float32, copy=True)
        valid = out[:, 0] >= 0
        x1 = out[valid, 1].copy()
        out[valid, 1] = 1.0 - out[valid, 3]
        out[valid, 3] = 1.0 - x1
        return img, out


def _crop_boxes(label, x0, y0, w, h, W, H, min_eject_coverage):
    """Boxes (normalized, on a W x H image) remapped into the pixel
    crop (x0, y0, w, h); a box keeping less than ``min_eject_coverage``
    of its area is ejected (cls = -1)."""
    out = np.array(label, np.float32, copy=True)
    valid = out[:, 0] >= 0
    if not np.any(valid):
        return out
    b = out[valid, 1:5] * [W, H, W, H]
    area = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(
        b[:, 3] - b[:, 1], 0)
    ix1 = np.maximum(b[:, 0], x0)
    iy1 = np.maximum(b[:, 1], y0)
    ix2 = np.minimum(b[:, 2], x0 + w)
    iy2 = np.minimum(b[:, 3], y0 + h)
    inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
    keep = inter >= min_eject_coverage * np.maximum(area, 1e-10)
    nb = np.stack([np.clip((ix1 - x0) / w, 0, 1),
                   np.clip((iy1 - y0) / h, 0, 1),
                   np.clip((ix2 - x0) / w, 0, 1),
                   np.clip((iy2 - y0) / h, 0, 1)], axis=1)
    rows = np.where(valid)[0]
    out[rows, 1:5] = nb
    out[rows[~keep], 0] = -1.0
    return out


class DetRandomCropAug(DetAugmenter):
    """Random crop constrained to keep at least ``min_object_covered``
    of some object, sampling aspect ratio and area like the reference
    (ref detection.py:152, the TF sample_distorted_bounding_box recipe).
    Falls through (no crop) when no valid crop is found in
    ``max_attempts``."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _sample(self, H, W, label):
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            area = pyrandom.uniform(*self.area_range) * H * W
            w = int(round(np.sqrt(area * ratio)))
            h = int(round(np.sqrt(area / ratio)))
            if w > W or h > H or w < 1 or h < 1:
                continue
            x0 = pyrandom.randint(0, W - w)
            y0 = pyrandom.randint(0, H - h)
            valid = label[:, 0] >= 0
            if np.any(valid):
                b = label[valid, 1:5] * [W, H, W, H]
                area_obj = np.maximum(b[:, 2] - b[:, 0], 0) * \
                    np.maximum(b[:, 3] - b[:, 1], 0)
                ix = np.maximum(
                    np.minimum(b[:, 2], x0 + w) - np.maximum(b[:, 0], x0),
                    0)
                iy = np.maximum(
                    np.minimum(b[:, 3], y0 + h) - np.maximum(b[:, 1], y0),
                    0)
                cover = ix * iy / np.maximum(area_obj, 1e-10)
                if cover.max() < self.min_object_covered:
                    continue
            return x0, y0, w, h
        return None

    def __call__(self, src, label):
        img = _as_np(src)
        H, W = img.shape[:2]
        label = np.asarray(label, np.float32)
        crop = self._sample(H, W, label)
        if crop is None:
            return img, label
        x0, y0, w, h = crop
        out = _crop_boxes(label, x0, y0, w, h, W, H,
                          self.min_eject_coverage)
        return img[y0:y0 + h, x0:x0 + w], out


class DetRandomPadAug(DetAugmenter):
    """Random expansion: paste the image at a random offset on a larger
    ``pad_val`` canvas, shrinking boxes accordingly
    (ref detection.py:323)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        img = _as_np(src)
        H, W = img.shape[:2]
        label = np.asarray(label, np.float32)
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            area = pyrandom.uniform(*self.area_range) * H * W
            nw = int(round(np.sqrt(area * ratio)))
            nh = int(round(np.sqrt(area / ratio)))
            if nw < W or nh < H:
                continue
            x0 = pyrandom.randint(0, nw - W)
            y0 = pyrandom.randint(0, nh - H)
            canvas = np.empty((nh, nw, img.shape[2]), img.dtype)
            canvas[...] = np.asarray(self.pad_val, img.dtype)
            canvas[y0:y0 + H, x0:x0 + W] = img
            out = np.array(label, np.float32, copy=True)
            valid = out[:, 0] >= 0
            out[valid, 1] = (out[valid, 1] * W + x0) / nw
            out[valid, 3] = (out[valid, 3] * W + x0) / nw
            out[valid, 2] = (out[valid, 2] * H + y0) / nh
            out[valid, 4] = (out[valid, 4] * H + y0) / nh
            return canvas, out
        return img, label


class _DetResizeAug(DetAugmenter):
    """Force-resize to (w, h): normalized boxes are invariant."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src, label):
        img = _as_np(src)
        out = imresize(nd.array(img), self.size[0], self.size[1],
                       self.interp)
        return _as_np(out), label


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0.0):
    """One DetRandomSelectAug over per-parameter DetRandomCropAug
    choices; scalar params broadcast (ref detection.py:417)."""
    def listify(v):
        return v if isinstance(v, (list, tuple)) and v \
            and isinstance(v[0], (list, tuple)) else [v]

    covered = min_object_covered if isinstance(
        min_object_covered, (list, tuple)) else [min_object_covered]
    ratios = listify(aspect_ratio_range)
    areas = listify(area_range)
    ejects = min_eject_coverage if isinstance(
        min_eject_coverage, (list, tuple)) else [min_eject_coverage]
    n = max(len(covered), len(ratios), len(areas), len(ejects))

    def at(seq, i):
        return seq[i] if i < len(seq) else seq[-1]

    crops = [DetRandomCropAug(
        min_object_covered=at(covered, i),
        aspect_ratio_range=at(ratios, i), area_range=at(areas, i),
        min_eject_coverage=at(ejects, i), max_attempts=max_attempts)
        for i in range(n)]
    return DetRandomSelectAug(crops, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None,
                       std=None, brightness=0, contrast=0, saturation=0,
                       pca_noise=0, hue=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Detection augmenter list (ref detection.py:482): optional
    random pad/crop (with probabilities ``rand_pad``/``rand_crop``),
    mirror, force-resize to data_shape, then borrowed color/cast/
    normalize augmenters."""
    auglist = []
    if resize > 0:
        # resize-shorter keeps aspect; normalized boxes unaffected
        auglist.append(DetBorrowAug(
            __import__("mxnet_tpu.image.image", fromlist=["ResizeAug"])
            .ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = CreateMultiRandCropAugmenter(
            min_object_covered=min_object_covered,
            aspect_ratio_range=aspect_ratio_range,
            area_range=(min(area_range[0], 1.0),
                        min(area_range[1], 1.0)),
            min_eject_coverage=min_eject_coverage,
            max_attempts=max_attempts, skip_prob=1 - rand_crop)
        auglist.append(crop)
    if rand_pad > 0:
        pad = DetRandomPadAug(
            aspect_ratio_range=aspect_ratio_range,
            area_range=(max(area_range[0], 1.0),
                        max(area_range[1], 1.0)),
            max_attempts=max_attempts, pad_val=pad_val)
        auglist.append(DetRandomSelectAug([pad], skip_prob=1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # force to the network's input size LAST among geometry augs
    auglist.append(_DetResizeAug((data_shape[2], data_shape[1]),
                                 inter_method))
    color = CreateAugmenter(
        (data_shape[0], data_shape[1], data_shape[2]), resize=0,
        rand_crop=False, rand_mirror=False, mean=mean, std=std,
        brightness=brightness, contrast=contrast, saturation=saturation,
        hue=hue, pca_noise=pca_noise, rand_gray=rand_gray)
    for aug in color:
        name = aug.__class__.__name__
        if name in ("CenterCropAug", "RandomCropAug"):
            continue  # geometry handled above
        auglist.append(DetBorrowAug(aug))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator over .rec / .lst / in-memory lists
    (ref detection.py:624).

    List-format labels are the im2rec detection layout:
    ``[header_width, object_width, (cls, x1, y1, x2, y2, ...)*N]``;
    batches are padded to ``(batch, max_objects, object_width)`` with
    -1 rows.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=".", shuffle=False,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="label", **kwargs):
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         shuffle=shuffle, aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name)
        self.auglist = aug_list if aug_list is not None \
            else CreateDetAugmenter(data_shape, **kwargs)
        self.label_shape = self._estimate_label_shape()
        self._label_name = label_name

    # -- label plumbing --------------------------------------------------
    def _parse_label(self, raw):
        """im2rec detection layout -> (N, object_width) array."""
        raw = np.asarray(raw, np.float32).reshape(-1)
        if raw.size < 2:
            raise MXNetError("detection label too short: %r" % (raw,))
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if obj_width < 5:
            raise MXNetError(
                "object width %d < 5 (cls,x1,y1,x2,y2)" % obj_width)
        body = raw[header_width:]
        if body.size % obj_width:
            raise MXNetError(
                "label body %d not a multiple of object width %d"
                % (body.size, obj_width))
        return body.reshape(-1, obj_width).copy()

    def _estimate_label_shape(self):
        """Scan every label once to derive (max_objects, object_width),
        like the reference (detection.py _estimate_label_shape) — no
        hardcoded pad that could truncate crowded images or clip wide
        object rows."""
        max_objs, width = 0, 5
        if self.imglist is not None:
            for _, raw in self.imglist:
                lab = self._parse_label(raw)
                max_objs = max(max_objs, lab.shape[0])
                width = max(width, lab.shape[1])
        elif getattr(self, "_rec", None) is not None:
            from .. import recordio
            if self._keys is not None:
                recs = (self._rec.read_idx(k) for k in self._keys)
            else:
                self._rec.reset()
                recs = iter(self._rec.read, None)
            for rec in recs:
                header, _ = recordio.unpack(rec)
                lab = self._parse_label(header.label)
                max_objs = max(max_objs, lab.shape[0])
                width = max(width, lab.shape[1])
            if self._keys is None:
                self._rec.reset()
        return (max(max_objs, 1), width)

    @property
    def provide_label(self):
        return [_io.DataDesc(self._label_name,
                             (self.batch_size,) + self.label_shape)]

    @provide_label.setter
    def provide_label(self, value):      # base class sets a default
        pass

    def reshape(self, data_shape=None, label_shape=None):
        """Change data/label shapes between epochs
        (ref detection.py reshape)."""
        if data_shape is not None:
            self.data_shape = tuple(data_shape)
        if label_shape is not None:
            self.label_shape = tuple(label_shape)

    def sync_label_shape(self, it, verbose=False):
        """Synchronize label padding with another ImageDetIter (train /
        val pairs must agree) and return the harmonized shape."""
        assert isinstance(it, ImageDetIter)
        shape = (max(self.label_shape[0], it.label_shape[0]),
                 max(self.label_shape[1], it.label_shape[1]))
        self.label_shape = shape
        it.label_shape = shape
        return shape

    # -- iteration -------------------------------------------------------
    def _read_det_sample(self, i):
        if self.imglist is not None:
            from .image import imread
            import os
            fname, raw = self.imglist[self._order[i]]
            img = imread(os.path.join(self._root, fname))
            label = self._parse_label(raw)
        else:
            from .. import recordio
            if self._keys is not None:
                rec = self._rec.read_idx(self._keys[self._order[i]])
            else:
                rec = self._rec.read()
                if rec is None:
                    raise StopIteration
            from .image import imdecode
            header, buf = recordio.unpack(rec)
            img = imdecode(buf)
            label = self._parse_label(header.label)
        return img, label

    def next(self):
        n = len(self._order) if self._order is not None else None
        if n is not None and self._cursor + self.batch_size > n:
            raise StopIteration
        c, h, w = self.data_shape
        pw, ow = self.label_shape
        data = np.zeros((self.batch_size, c, h, w), np.float32)
        labels = np.full((self.batch_size, pw, ow), -1.0, np.float32)
        for k in range(self.batch_size):
            img, label = self._read_det_sample(self._cursor + k)
            img = _as_np(img).astype(np.float32)
            for aug in self.auglist:
                img, label = aug(img, label) if isinstance(
                    aug, DetAugmenter) else (aug(img), label)
            img = _as_np(img)
            if img.shape[:2] != (h, w):
                img = _as_np(imresize(nd.array(img), w, h, 2))
            data[k] = np.transpose(img, (2, 0, 1))
            if label.shape[1] > ow:
                raise MXNetError(
                    "object width %d exceeds label_shape width %d; call "
                    "reshape(label_shape=...) or sync_label_shape first"
                    % (label.shape[1], ow))
            m = min(label.shape[0], pw)
            labels[k, :m, :label.shape[1]] = label[:m]
        self._cursor += self.batch_size
        return _io.DataBatch(data=[nd.array(data)],
                             label=[nd.array(labels)], pad=0)

    def draw_next(self, color=(255, 0, 0), thickness=2):
        """Yield augmented images (HWC uint8 numpy) with their boxes
        drawn — the reference's debug visualization, minus cv2 text."""
        while True:
            try:
                batch = self.next()
            except StopIteration:
                return
            imgs = batch.data[0].asnumpy().transpose(0, 2, 3, 1)
            labs = batch.label[0].asnumpy()
            for img, lab in zip(imgs, labs):
                canvas = np.clip(img, 0, 255).astype(np.uint8).copy()
                H, W = canvas.shape[:2]
                for row in lab:
                    if row[0] < 0:
                        continue
                    x1, y1, x2, y2 = (row[1] * W, row[2] * H,
                                      row[3] * W, row[4] * H)
                    x1, y1, x2, y2 = map(int, (x1, y1, x2, y2))
                    t = thickness
                    canvas[y1:y2, x1:x1 + t] = color
                    canvas[y1:y2, max(x2 - t, 0):x2] = color
                    canvas[y1:y1 + t, x1:x2] = color
                    canvas[max(y2 - t, 0):y2, x1:x2] = color
                yield canvas
