"""Image IO + augmentation (parity: python/mxnet/image/image.py +
src/io/image_aug_default.cc).

Decode/augment run on the host CPU (cv2 or PIL when available); the
result feeds the device as one batched transfer — the same division of
labor as the reference's OMP-parallel ImageRecordIOParser2.
"""
from __future__ import annotations

import os
import random as pyrandom

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from .. import io as _io
from .. import recordio

__all__ = ["imread", "imdecode", "imresize", "fixed_crop", "center_crop",
           "random_crop", "resize_short", "color_normalize",
           "CreateAugmenter", "Augmenter", "ResizeAug", "ForceResizeAug",
           "RandomCropAug", "CenterCropAug", "HorizontalFlipAug", "CastAug",
           "ColorNormalizeAug", "BrightnessJitterAug", "ContrastJitterAug",
           "SaturationJitterAug", "ImageIter"]


def _backend():
    try:
        import cv2
        return "cv2", cv2
    except ImportError:
        pass
    try:
        from PIL import Image
        return "pil", Image
    except ImportError:
        raise MXNetError("image ops require cv2 or PIL; neither is "
                         "available")


def imread(filename, flag=1, to_rgb=True):
    """Read image file → HWC uint8 NDArray (reference: image.py imread)."""
    kind, mod = _backend()
    if kind == "cv2":
        img = mod.imread(filename, mod.IMREAD_COLOR if flag else
                         mod.IMREAD_GRAYSCALE)
        if img is None:
            raise MXNetError("imread failed: %s" % filename)
        if flag and to_rgb:
            img = img[:, :, ::-1]
        if not flag:
            img = img[:, :, None]
    else:
        im = mod.open(filename)
        im = im.convert("RGB" if flag else "L")
        img = np.asarray(im)
        if not flag:
            img = img[:, :, None]
    return nd.array(np.ascontiguousarray(img), dtype=np.uint8)


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode image bytes (reference: src/io/image_io.cc imdecode)."""
    kind, mod = _backend()
    if isinstance(buf, nd.NDArray):
        buf = buf.asnumpy().tobytes()
    if kind == "cv2":
        img = mod.imdecode(np.frombuffer(buf, dtype=np.uint8),
                           mod.IMREAD_COLOR if flag else
                           mod.IMREAD_GRAYSCALE)
        if img is None:
            raise MXNetError("imdecode failed")
        if flag and to_rgb:
            img = img[:, :, ::-1]
        if not flag:
            img = img[:, :, None]
    else:
        import io as _pyio
        im = mod.open(_pyio.BytesIO(buf))
        im = im.convert("RGB" if flag else "L")
        img = np.asarray(im)
        if not flag:
            img = img[:, :, None]
    return nd.array(np.ascontiguousarray(img), dtype=np.uint8)


def imresize(src, w, h, interp=1):
    import jax
    data = src._data.astype("float32") if isinstance(src, nd.NDArray) \
        else np.asarray(src, dtype="float32")
    method = "bilinear" if interp else "nearest"
    out = jax.image.resize(data, (h, w, data.shape[2]), method)
    return nd.NDArray(out.astype(src.dtype if hasattr(src, "dtype")
                                 else "uint8"))


def resize_short(src, size, interp=2):
    h, w = src.shape[0], src.shape[1]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp=1 if interp else 0)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp=1)
    return out


def random_crop(src, size, interp=2):
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = size
    x0 = pyrandom.randint(0, max(0, w - new_w))
    y0 = pyrandom.randint(0, max(0, h - new_h))
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


class Augmenter:
    """Base augmenter (reference: image.py:576)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return src.flip(axis=1)
        return src


class CastAug(Augmenter):
    def __init__(self, typ='float32'):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = mean if mean is None or isinstance(mean, nd.NDArray) \
            else nd.array(mean)
        self.std = std if std is None or isinstance(std, nd.NDArray) \
            else nd.array(std)

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return src * alpha


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast
        self.coef = nd.array([[[0.299, 0.587, 0.114]]])

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        gray = src * self.coef
        gray = (3.0 * (1.0 - alpha) / gray.size) * gray.sum()
        return src * alpha + gray


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation
        self.coef = nd.array([[[0.299, 0.587, 0.114]]])

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        gray = src * self.coef
        gray = gray.sum(axis=2, keepdims=True)
        return src * alpha + gray * (1.0 - alpha)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter list (reference: image.py:744)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast:
        auglist.append(ContrastJitterAug(contrast))
    if saturation:
        auglist.append(SaturationJitterAug(saturation))
    if mean is True:
        mean = nd.array([123.68, 116.28, 103.53])
    elif mean is not None and not isinstance(mean, nd.NDArray):
        mean = nd.array(mean)
    if std is True:
        std = nd.array([58.395, 57.12, 57.375])
    elif std is not None and not isinstance(std, nd.NDArray):
        std = nd.array(std)
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(_io.DataIter):
    """Image iterator with augmentation over RecordIO or image lists
    (reference: image.py:1050 + src/io/iter_image_recordio_2.cc)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root='.',
                 shuffle=False, aug_list=None, imglist=None,
                 data_name='data', label_name='softmax_label', **kwargs):
        super().__init__(batch_size)
        assert len(data_shape) == 3
        self.data_shape = tuple(data_shape)
        self.batch_size = batch_size
        self.label_width = label_width
        self._shuffle = shuffle
        self._data_name = data_name
        self._label_name = label_name
        self.auglist = aug_list if aug_list is not None \
            else CreateAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ('resize', 'rand_crop', 'rand_resize',
                         'rand_mirror', 'mean', 'std', 'brightness',
                         'contrast', 'saturation', 'hue', 'pca_noise',
                         'rand_gray', 'inter_method')})
        self._rec = None
        self.imglist = None
        if path_imgrec is not None:
            idx_path = os.path.splitext(path_imgrec)[0] + '.idx'
            if os.path.exists(idx_path):
                self._rec = recordio.MXIndexedRecordIO(idx_path,
                                                       path_imgrec, 'r')
                self._keys = list(self._rec.keys)
            else:
                self._rec = recordio.MXRecordIO(path_imgrec, 'r')
                self._keys = None
        elif path_imglist is not None or imglist is not None:
            items = []
            if path_imglist is not None:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split('\t')
                        label = [float(x) for x in parts[1:-1]]
                        items.append((parts[-1], label))
            else:
                for entry in imglist:
                    items.append((entry[-1], [float(x)
                                              for x in entry[:-1]]))
            self.imglist = items
            self._root = path_root
        else:
            raise MXNetError("ImageIter needs path_imgrec, path_imglist or "
                             "imglist")
        self._order = None
        self.reset()

    @property
    def provide_data(self):
        return [_io.DataDesc(self._data_name,
                             (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [_io.DataDesc(self._label_name, shape)]

    def reset(self):
        self._cursor = 0
        if self.imglist is not None:
            self._order = list(range(len(self.imglist)))
        elif self._keys is not None:
            self._order = list(range(len(self._keys)))
        else:
            self._rec.reset()
            self._order = None
        if self._shuffle and self._order is not None:
            pyrandom.shuffle(self._order)

    def _read_sample(self, i):
        if self.imglist is not None:
            fname, label = self.imglist[self._order[i]]
            img = imread(os.path.join(self._root, fname))
        elif self._keys is not None:
            rec = self._rec.read_idx(self._keys[self._order[i]])
            header, buf = recordio.unpack(rec)
            img = imdecode(buf)
            label = header.label
        else:
            rec = self._rec.read()
            if rec is None:
                raise StopIteration
            header, buf = recordio.unpack(rec)
            img = imdecode(buf)
            label = header.label
        for aug in self.auglist:
            img = aug(img)
        return img, np.asarray(label, dtype=np.float32).reshape(-1)

    def next(self):
        n = len(self._order) if self._order is not None else None
        if n is not None and self._cursor + self.batch_size > n:
            raise StopIteration
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), dtype=np.float32)
        batch_label = np.zeros((self.batch_size, self.label_width),
                               dtype=np.float32)
        for k in range(self.batch_size):
            img, label = self._read_sample(self._cursor + k)
            arr = img.asnumpy() if isinstance(img, nd.NDArray) else img
            batch_data[k] = np.transpose(arr, (2, 0, 1))
            batch_label[k, :len(label)] = label[:self.label_width]
        self._cursor += self.batch_size
        if self.label_width == 1:
            batch_label = batch_label.reshape(-1)
        return _io.DataBatch(data=[nd.array(batch_data)],
                             label=[nd.array(batch_label)], pad=0)

    def iter_next(self):
        try:
            self._next_cache = self.next()
            return True
        except StopIteration:
            return False
