"""Image utilities (parity: python/mxnet/image/)."""
from .image import (imread, imdecode, imresize, fixed_crop, center_crop,
                    random_crop, resize_short, color_normalize,
                    CreateAugmenter, Augmenter, ResizeAug, ForceResizeAug,
                    RandomCropAug, CenterCropAug, HorizontalFlipAug,
                    CastAug, ColorNormalizeAug, BrightnessJitterAug,
                    ContrastJitterAug, SaturationJitterAug, ImageIter)
from .detection import (DetAugmenter, DetBorrowAug, DetRandomSelectAug,
                        DetHorizontalFlipAug, DetRandomCropAug,
                        DetRandomPadAug, CreateMultiRandCropAugmenter,
                        CreateDetAugmenter, ImageDetIter)
