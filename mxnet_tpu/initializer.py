"""Weight initializers (API parity: python/mxnet/initializer.py).

Own structure: name-suffix routing is a declarative table
(`_SUFFIX_ROUTES`) rather than an if/elif chain, and every built-in
initializer is a tiny `_generate(name, shape) -> ndarray` under a
shared write path. Subclasses may still override ``_init_weight(name,
arr)`` — the documented extension point the reference established —
and everything funnels through one `_set` so dtype/placement handling
lives in a single place.
"""
from __future__ import annotations

import json
import re

import numpy as np

from .base import Registry, MXNetError

__all__ = ["InitDesc", "Initializer", "register", "Zero", "One", "Constant",
           "Uniform", "Normal", "Orthogonal", "Xavier", "MSRAPrelu",
           "Bilinear", "LSTMBias", "Mixed", "create"]

_REG: Registry = Registry("initializer", case_sensitive=False)


class InitDesc(str):
    """Parameter name enriched with attrs + the global initializer
    (reference: initializer.py:37)."""

    def __new__(cls, name, attrs=None, global_init=None):
        self = str.__new__(cls, name)
        self.attrs = attrs or {}
        self.global_init = global_init
        return self


def register(klass):
    _REG.register(klass.__name__)(klass)
    return klass


# suffix → handler method, first match wins (order matters: the
# reference's chain is reproduced as data)
_SUFFIX_ROUTES = (
    (("weight",), "_init_weight"),
    (("bias",), "_init_bias"),
    (("gamma",), "_init_gamma"),
    (("beta",), "_init_beta"),
    (("moving_mean", "running_mean", "moving_inv_var", "moving_avg",
      "min", "max"), "_init_zero"),
    (("moving_var", "running_var"), "_init_one"),
)


class Initializer:
    """Base initializer: routes a parameter by name suffix, fills the
    array in place (reference: initializer.py:95)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose, self._print_func = False, None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose, self._print_func = verbose, print_func
        return self

    def dumps(self):
        """Serialized [name, kwargs] form consumed by ``create``."""
        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError(
                "initializer expects a parameter name (str/InitDesc), "
                "got %s" % type(desc))
        if isinstance(desc, InitDesc) and desc.global_init is None:
            desc.global_init = self
        override = getattr(desc, "attrs", {}).get("__init__")
        if override:
            kind, kwargs = json.loads(override)
            create(kind, **kwargs)._init_weight(desc, arr)
            return
        for suffixes, method in _SUFFIX_ROUTES:
            if str(desc).endswith(suffixes):
                getattr(self, method)(desc, arr)
                return
        self._init_default(desc, arr)

    # -- write path -------------------------------------------------------
    def _set(self, arr, value):
        from .ndarray import array as nd_array
        arr[:] = nd_array(np.asarray(value, dtype=arr.dtype))

    # -- per-kind handlers (subclass extension points) --------------------
    def _init_zero(self, name, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_one(self, name, arr):
        self._set(arr, np.ones(arr.shape))

    _init_bias = _init_zero
    _init_beta = _init_zero
    _init_gamma = _init_one

    def _init_weight(self, name, arr):
        self._set(arr, self._generate(name, arr.shape))

    def _generate(self, name, shape):
        raise NotImplementedError(
            "%s must implement _generate or override _init_weight"
            % type(self).__name__)

    def _init_default(self, name, arr):
        raise ValueError(
            "no initialization rule for %r: only *weight/*bias/*gamma/"
            "*beta (and BatchNorm stats) route automatically — pass an "
            "explicit Initializer for this array" % str(name))


class _EverywhereMixin:
    """Initializers that apply to any parameter kind, not just weights."""

    def _init_default(self, name, arr):
        self._init_weight(name, arr)


@register
class Zero(_EverywhereMixin, Initializer):
    def _generate(self, name, shape):
        return np.zeros(shape)


@register
class One(_EverywhereMixin, Initializer):
    def _generate(self, name, shape):
        return np.ones(shape)


@register
class Constant(_EverywhereMixin, Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _generate(self, name, shape):
        return np.full(shape, self.value)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _generate(self, name, shape):
        return np.random.uniform(-self.scale, self.scale, shape)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _generate(self, name, shape):
        return np.random.normal(0.0, self.sigma, shape)


@register
class Orthogonal(Initializer):
    """SVD-orthogonalized random matrix (reference: initializer.py:482)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale, self.rand_type = scale, rand_type

    def _generate(self, name, shape):
        rows, cols = shape[0], int(np.prod(shape[1:]))
        seed = np.random.uniform(-1, 1, (rows, cols)) \
            if self.rand_type == "uniform" \
            else np.random.normal(0, 1, (rows, cols))
        u, _, vt = np.linalg.svd(seed, full_matrices=False)
        basis = u if u.shape == seed.shape else vt
        return (self.scale * basis).reshape(shape)


def _fans(name, shape):
    """(fan_in, fan_out) with conv receptive-field scaling."""
    if len(shape) < 2:
        raise ValueError(
            "Xavier-family initializers need >= 2 dims; %r has shape %s"
            % (str(name), shape))
    field = np.prod(shape[2:]) if len(shape) > 2 else 1.0
    return shape[1] * field, shape[0] * field


@register
class Xavier(Initializer):
    """Glorot scaling (reference: initializer.py:540)."""

    _FACTORS = {
        "avg": lambda fi, fo: (fi + fo) / 2.0,
        "in": lambda fi, fo: fi,
        "out": lambda fi, fo: fo,
    }

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type, self.factor_type = rnd_type, factor_type
        self.magnitude = float(magnitude)

    def _generate(self, name, shape):
        try:
            factor = self._FACTORS[self.factor_type](*_fans(name, shape))
        except KeyError:
            raise ValueError(
                "factor_type must be avg/in/out, got %r"
                % (self.factor_type,))
        bound = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            return np.random.uniform(-bound, bound, shape)
        if self.rnd_type == "gaussian":
            return np.random.normal(0.0, bound, shape)
        raise ValueError("rnd_type must be uniform/gaussian, got %r"
                         % (self.rnd_type,))


@register
class MSRAPrelu(Xavier):
    """He/MSRA init specialised for PReLU slopes
    (reference: initializer.py:626)."""

    def __init__(self, factor_type="avg", slope=0.25):
        super().__init__("gaussian", factor_type, 2.0 / (1 + slope ** 2))
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel for deconvolution
    (reference: initializer.py:657)."""

    def _generate(self, name, shape):
        kw = shape[3]
        kh = shape[2]
        f = np.ceil(kw / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        xs = np.arange(kw)
        ys = np.arange(kh)
        kernel = np.outer(1 - np.abs(ys / f - c), 1 - np.abs(xs / f - c))
        return np.broadcast_to(kernel, shape)


@register
class LSTMBias(Initializer):
    """1.0 on the forget-gate quarter, zero elsewhere
    (reference: initializer.py:685)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _generate(self, name, shape):
        vec = np.zeros(shape, dtype="float32")
        h = shape[0] // 4
        vec[h:2 * h] = self.forget_bias
        return vec

    def _init_default(self, name, arr):
        self._init_weight(name, arr)

    _init_bias = Initializer._init_weight


@register
class Mixed(Initializer):
    """First regex pattern that matches a name picks its initializer
    (reference: initializer.py:286)."""

    def __init__(self, patterns, initializers):
        super().__init__()
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must pair up")
        self.map = [(re.compile(p), ini)
                    for p, ini in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for pattern, ini in self.map:
            if pattern.match(str(name)):
                ini(name, arr)
                return
        raise ValueError(
            "parameter %r matched none of the Mixed patterns; add a "
            "'.*' catch-all if that is intended" % str(name))


# reference alias names (@mx.init.register alias strings)
for _alias, _cls in (("zeros", Zero), ("ones", One), ("gaussian", Normal),
                     ("msra", MSRAPrelu)):
    _REG.register(_alias, allow_override=True)(_cls)


def create(name, **kwargs):
    """Resolve an initializer from an instance, name, or alias."""
    if isinstance(name, Initializer):
        return name
    cls = _REG.find(str(name))
    if cls is None:
        raise MXNetError("unknown initializer %r" % (name,))
    return cls(**kwargs)
