"""Weight initializers (parity: python/mxnet/initializer.py)."""
from __future__ import annotations

import json
import re

import numpy as np

from .base import Registry, MXNetError

__all__ = ["InitDesc", "Initializer", "register", "Zero", "One", "Constant",
           "Uniform", "Normal", "Orthogonal", "Xavier", "MSRAPrelu",
           "Bilinear", "LSTMBias", "Mixed", "create"]

_REG: Registry = Registry("initializer", case_sensitive=False)


class InitDesc(str):
    """Name + attrs descriptor passed to initializers
    (reference: initializer.py:37)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


def register(klass):
    _REG.register(klass.__name__)(klass)
    return klass


class Initializer:
    """Base initializer (reference: initializer.py:95)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be a string or InitDesc")
        if getattr(desc, "global_init", None) is None and \
                isinstance(desc, InitDesc):
            desc.global_init = self
        init = getattr(desc, "attrs", {}).get("__init__", "")
        if init:
            klass, kwargs = json.loads(init)
            create(klass, **kwargs)._init_weight(desc, arr)
            return
        name = str(desc)
        if name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(name, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(name, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(name, arr)
        elif name.endswith("min") or name.endswith("max"):
            self._init_zero(name, arr)
        else:
            self._init_default(name, arr)

    def _set(self, arr, np_value):
        from .ndarray import array as nd_array
        arr[:] = nd_array(np.asarray(np_value, dtype=arr.dtype))

    def _init_zero(self, name, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_one(self, name, arr):
        self._set(arr, np.ones(arr.shape))

    def _init_bias(self, name, arr):
        self._init_zero(name, arr)

    def _init_gamma(self, name, arr):
        self._init_one(name, arr)

    def _init_beta(self, name, arr):
        self._init_zero(name, arr)

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override it")

    def _init_default(self, name, arr):
        raise ValueError(
            'Unknown initialization pattern for %s. Default initialization '
            'is now limited to "weight", "bias", "gamma" and "beta". Pass an '
            'explicit Initializer to init these arrays.' % name)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        self._init_zero(_, arr)

    _init_default = _init_weight


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        self._init_one(_, arr)

    _init_default = _init_weight


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        self._set(arr, np.full(arr.shape, self.value))

    _init_default = _init_weight


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        self._set(arr, np.random.uniform(-self.scale, self.scale, arr.shape))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        self._set(arr, np.random.normal(0, self.sigma, arr.shape))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1, 1, (nout, nin))
        else:
            tmp = np.random.normal(0, 1, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        self._set(arr, (self.scale * q).reshape(arr.shape))


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference: initializer.py:540)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.
        if len(shape) < 2:
            raise ValueError(
                'Xavier initializer cannot be applied to vector {0}. It '
                'requires at least 2D.'.format(name))
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, np.random.uniform(-scale, scale, shape))
        elif self.rnd_type == "gaussian":
            self._set(arr, np.random.normal(0, scale, shape))
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2. / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        weight = np.zeros(arr.shape, dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.)
        c = (2 * f - 1 - f % 2) / (2. * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight)


@register
class LSTMBias(Initializer):
    """Forget-gate bias 1.0, rest 0 (reference: initializer.py:685)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype="float32")
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden:2 * num_hidden] = self.forget_bias
        self._set(arr, b)

    _init_default = _init_weight
    _init_bias = _init_weight


@register
class Mixed(Initializer):
    def __init__(self, patterns, initializers):
        super().__init__()
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(str(name)):
                init(name, arr)
                return
        raise ValueError('Parameter name %s did not match any pattern.'
                         % name)


# registry aliases matching the reference (@init.register with alias)
_REG.register("zeros", allow_override=True)(Zero)
_REG.register("ones", allow_override=True)(One)
_REG.register("gaussian", allow_override=True)(Normal)
_REG.register("msra", allow_override=True)(MSRAPrelu)


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    cls = _REG.find(str(name))
    if cls is None:
        raise MXNetError("Unknown initializer %s" % name)
    return cls(**kwargs)
