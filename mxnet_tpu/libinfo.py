"""Library/version info (parity: python/mxnet/libinfo.py).

The reference locates ``libmxnet.so``; here the native component is
the optional IO runtime (``native/build/libmxtpu_io.so``) and the
compute "library" is XLA itself, so ``find_lib_path`` returns the
paths of whichever native artifacts exist (possibly empty — the
framework is fully functional without them)."""
from __future__ import annotations

import os

__all__ = ["find_lib_path", "find_include_path", "__version__"]

__version__ = "0.1.0"


def find_lib_path():
    """Paths of built native libraries (may be empty)."""
    from .io.native import lib_path
    p = lib_path()
    return [p] if os.path.exists(p) else []


def find_include_path():
    """Native source directory (the C ABI lives in the .cc files; no
    separate headers are installed)."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    inc = os.path.join(here, "native")
    return inc if os.path.isdir(inc) else ""
