"""Typed ``MXNET_*`` environment-variable registry.

Every environment knob the framework reads is DECLARED here once —
name, type, default, one-line doc — and read everywhere else through
the typed accessors (:func:`get_bool` / :func:`get_int` /
:func:`get_float` / :func:`get_str` / :func:`get_path`).  This replaces
the point-of-use ``base.get_env``/``os.environ`` reads that grew one
per PR, and extends the ``MXNET_BUCKET_LADDER`` precedent (a malformed
value raises :class:`MXNetError` NAMING the variable, instead of being
silently swallowed into a default) to the whole surface:

- a read of an UNDECLARED ``MXNET_*`` name raises — a typo'd knob
  fails loudly at the read site instead of silently using defaults;
- a value that does not parse as the declared type raises
  ``MXNetError("MXNET_FOO='x': ...")`` — the operator is told exactly
  which variable to fix;
- accessors are type-checked against the declaration, so a knob
  cannot drift between int-at-one-site / float-at-another;
- reads stay POINT-OF-USE (nothing is cached here): tests and
  benchmarks that flip a variable mid-process keep working.

The ``env-registry`` mxlint rule (``mxnet_tpu/tools/lint``) enforces
that no framework module reads ``MXNET_*`` any other way, and
``python -m mxnet_tpu.tools.lint --envs`` renders the registry as the
environment-variable reference (the auto-derived successor of the
reference's ``docs/faq/env_var.md``).

Declarations keep insertion order; :func:`render_reference` groups by
the ``group`` tag for the generated docs table.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

from .base import MXNetError

__all__ = [
    "EnvVar", "declare", "declared", "registry", "get_bool", "get_int",
    "get_float", "get_str", "get_path", "get_raw", "snapshot",
    "render_reference",
]

_TRUE = ("1", "true", "yes", "on")
_FALSE = ("0", "false", "no", "off")


class EnvVar:
    """One declared knob: ``name``, ``kind`` (bool/int/float/str/path),
    ``default`` (returned when unset), ``doc`` (one line, rendered into
    the generated reference), ``group`` (reference section)."""

    __slots__ = ("name", "kind", "default", "doc", "group")

    def __init__(self, name, kind, default, doc, group):
        self.name = name
        self.kind = kind
        self.default = default
        self.doc = doc
        self.group = group

    def __repr__(self):
        return "EnvVar(%s, %s, default=%r)" % (self.name, self.kind,
                                               self.default)


_REGISTRY: Dict[str, EnvVar] = {}


def declare(name, kind, default, doc, group="misc"):
    if kind not in ("bool", "int", "float", "str", "path"):
        raise MXNetError("envs.declare(%s): unknown kind %r"
                         % (name, kind))
    if name in _REGISTRY:
        raise MXNetError("envs.declare(%s): already declared" % name)
    var = EnvVar(name, kind, default, doc, group)
    _REGISTRY[name] = var
    return var


def declared(name):
    """True when ``name`` is a registered variable."""
    return name in _REGISTRY


def registry():
    """The declarations, in declaration order (read-only view)."""
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# the declarations — one line per knob, grouped like the generated docs
# ---------------------------------------------------------------------------

_G = "execution"
declare("MXNET_FUSED_STEP", "bool", True,
        "Compile forward+backward+optimizer update into one XLA "
        "program (eager fallback when off).", _G)
declare("MXNET_ENGINE_TYPE", "str", "ThreadedEnginePerDevice",
        "Reported execution-engine type (reference-parity knob; "
        "informational under XLA).", _G)
declare("MXNET_XLA_COMPILER_OPTIONS", "str", None,
        "Comma-separated k=v XLA compiler options applied at every "
        "compile; 'none' clears the built-in defaults.", _G)
declare("MXNET_DEFAULT_CONTEXT", "str", "",
        "Override the default device context: cpu / gpu / tpu.", _G)
declare("MXNET_INT64_TENSOR_SIZE", "bool", False,
        "Enable int64 tensor indexing (large-tensor support).", _G)
declare("MXNET_UPDATE_ON_KVSTORE", "bool", None,
        "Run optimizer updates on the kvstore instead of the worker "
        "(default depends on the kvstore type).", _G)

_G = "compile"
declare("MXNET_COMPILE_WATCH", "bool", False,
        "Stage every framework jit site explicitly: per-compile "
        "timing, recompile causes, storms, MFU.", _G)
declare("MXNET_COMPILE_STORM_K", "int", 3,
        "Compiles of one program within the storm window that fire "
        "the recompile-storm warning.", _G)
declare("MXNET_COMPILE_STORM_STEPS", "int", 50,
        "The recompile-storm window, in telemetry steps (watched "
        "dispatches without a run).", _G)
declare("MXNET_DEVICE_PEAK_FLOPS", "float", 0.0,
        "Per-device peak FLOP/s for MFU math (0 = use the built-in "
        "peak table).", _G)
declare("MXNET_DEVICE_PEAK_BW", "float", 0.0,
        "Per-device peak memory bandwidth bytes/s for BW-utilization "
        "math (0 = built-in table).", _G)
declare("MXNET_COMPILE_CACHE_DIR", "path", "",
        "Directory for the persistent on-disk compile cache; empty "
        "disables it.", _G)
declare("MXNET_COMPILE_CACHE_MB", "float", 512.0,
        "LRU byte cap for the on-disk compile cache, in MB.", _G)
declare("MXNET_COMPILE_CACHE_QUEUE", "int", 64,
        "Bounded depth of the compile-cache background store queue "
        "(overflow drops the store, entry stays cold).", _G)

_G = "telemetry"
declare("MXNET_TELEMETRY", "bool", False,
        "Auto-start a telemetry run at the first step.", _G)
declare("MXNET_TELEMETRY_FILE", "path", "",
        "JSONL sink for telemetry records; empty keeps records "
        "in-memory only.", _G)
declare("MXNET_TELEMETRY_RING", "int", 1024,
        "Ring size of the per-metric latency/MFU reservoirs.", _G)
declare("MXNET_TELEMETRY_MEM_INTERVAL", "int", 10,
        "Steps between host/device memory samples.", _G)
declare("MXNET_TELEMETRY_FLUSH_STEPS", "int", 50,
        "Steps between sink flushes.", _G)
declare("MXNET_TELEMETRY_MAX_RECORDS", "int", 100000,
        "In-memory record cap for sink-less runs (overflow drops and "
        "counts).", _G)
declare("MXNET_TELEMETRY_LIVE_BUFFERS", "int", 1,
        "Keep the last N flushed record buffers live for /metrics "
        "scrapes.", _G)
declare("MXNET_TRACE", "bool", False,
        "Arm the always-on request/step tracer.", _G)
declare("MXNET_TRACE_FILE", "path", "",
        "Perfetto-JSON sink the tracer exports to at exit/dump.", _G)
declare("MXNET_TRACE_RING", "int", 200000,
        "Bounded in-memory trace-event ring (oldest dropped).", _G)
declare("MXNET_TRACE_TRACKS", "int", 4096,
        "Cap on distinct trace tracks (request lanes).", _G)
declare("MXNET_TRACE_WIRE", "bool", True,
        "Propagate the serializable trace context across process "
        "boundaries (router dispatch, multihost exchange) while "
        "tracing is on; off keeps every wire payload byte-identical "
        "even with a local tracer armed.", _G)
declare("MXNET_FLIGHTREC_DIR", "path", "",
        "Arm the flight recorder: post-mortem bundles (trace ring, "
        "recent telemetry, env/compile/serving state, the triggering "
        "alert) land here on watchdog alerts and crash paths.", _G)
declare("MXNET_FLIGHTREC_MAX_BUNDLES", "int", 8,
        "Keep at most this many flight-recorder bundles (oldest "
        "deleted first).", _G)
declare("MXNET_FLIGHTREC_MAX_BYTES", "int", 16 << 20,
        "Total on-disk budget for flight-recorder bundles; oldest "
        "bundles are deleted until a new one fits.", _G)
declare("MXNET_FLIGHTREC_INTERVAL_MS", "int", 5000,
        "Rate limit between flight-recorder dumps; triggers inside "
        "the window are counted as suppressed, never stacked.", _G)
declare("MXNET_FLIGHTREC_RECORDS", "int", 256,
        "Last K telemetry records the flight recorder keeps in its "
        "bounded shadow ring for bundles.", _G)
declare("MXNET_PROFILER_MAX_EVENTS", "int", 1000000,
        "Host-profiler event cap; overflow increments "
        "profiler_events_dropped instead of growing forever.", _G)
declare("MXNET_METRICS_PORT", "int", 0,
        "Serve the live /metrics endpoint on this port (0 picks a "
        "free port when started explicitly; unset disables).", _G)
declare("MXNET_METRICS_HOST", "str", "",
        "Bind host for the /metrics endpoint (default 127.0.0.1).",
        _G)
declare("MXNET_WATCHDOG", "bool", False,
        "Arm the SLO watchdog over serving/training step health.", _G)
declare("MXNET_WATCHDOG_DRIFT", "float", 1.5,
        "Step-time drift factor over baseline that counts as a slow "
        "step.", _G)
declare("MXNET_WATCHDOG_WINDOW", "int", 20,
        "Sliding window (steps) for watchdog drift checks.", _G)
declare("MXNET_WATCHDOG_BASELINE", "int", 50,
        "Steps used to establish the watchdog's baseline step "
        "time.", _G)
declare("MXNET_WATCHDOG_SUSTAIN", "int", 10,
        "Consecutive slow windows before the watchdog fires.", _G)
declare("MXNET_WATCHDOG_SHED_RATE", "float", 0.3,
        "Fraction of low-priority serving load shed when the "
        "watchdog trips.", _G)
declare("MXNET_WATCHDOG_MIN_REQUESTS", "int", 20,
        "Minimum requests in a window before serving SLO checks "
        "apply.", _G)
declare("MXNET_WATCHDOG_QUEUE_FRAC", "float", 0.9,
        "Admission-queue occupancy fraction that counts as "
        "saturation.", _G)
declare("MXNET_WATCHDOG_SKEW", "float", 2.0,
        "Max replica service-time skew before the watchdog flags an "
        "unhealthy replica.", _G)
declare("MXNET_METER_FILE", "path", "",
        "JSONL ledger for per-request usage records "
        "(mxnet_tpu.metering); empty keeps the bounded in-memory "
        "tail only.", _G)
declare("MXNET_METER_FLUSH_EVERY", "int", 32,
        "Closed usage records between ledger appends and usage "
        "telemetry snapshots.", _G)
declare("MXNET_METER_MAX_RECORDS", "int", 100000,
        "In-memory cap on closed usage records (the ledger file is "
        "unbounded; the tail ring is not).", _G)

_G = "fault"
declare("MXNET_FAULT_PLAN", "str", "",
        "Deterministic fault-injection plan, e.g. "
        "'push:step=1:raise' (see fault.py).", _G)
declare("MXNET_FAULT_HANG_SECONDS", "float", 0.05,
        "Duration of an injected 'hang' fault.", _G)
declare("MXNET_NONFINITE_GUARD", "str", "",
        "Non-finite gradient policy: skip_step | scale_backoff | "
        "empty (off).", _G)
declare("MXNET_LOSS_SCALE", "float", 2.0 ** 15,
        "Initial loss scale for the scale_backoff guard.", _G)
declare("MXNET_LOSS_SCALE_WINDOW", "int", 2000,
        "Good steps between loss-scale growth attempts.", _G)
declare("MXNET_AMP_POLICY", "str", "",
        "Default AMP compute dtype for amp.DtypePolicy.from_env: "
        "bfloat16 | float16 | empty (off).", _G)
declare("MXNET_AMP_RULES", "str", "",
        "Ordered per-parameter dtype overrides for the AMP policy, "
        "'substring=dtype,...' — first match wins (see amp.py).", _G)
declare("MXNET_KVSTORE_TIMEOUT", "float", 60.0,
        "Seconds a collective may retry before "
        "CollectiveTimeoutError.", _G)
declare("MXNET_KVSTORE_RETRY_BACKOFF", "float", 0.05,
        "Initial collective retry backoff, seconds.", _G)
declare("MXNET_KVSTORE_RETRY_MAX_BACKOFF", "float", 2.0,
        "Backoff ceiling for collective retries, seconds.", _G)

_G = "parallel"
declare("MXNET_GRAD_OVERLAP", "bool", False,
        "Bucketed backward-ordered reduce-scatter + ZeRO-1 sharded "
        "update inside the compiled step.", _G)
declare("MXNET_GRAD_BUCKET_MB", "float", 4.0,
        "Gradient-bucket size cap for the overlap path, MB.", _G)
declare("MXNET_PARAM_SHARD", "bool", False,
        "Keep parameters FSDP-sharded at rest with just-in-time "
        "entry gathers.", _G)
declare("MXNET_TPU_COORDINATOR", "str", None,
        "Multi-process coordinator address for "
        "jax.distributed.initialize.", _G)
declare("MXNET_TPU_WORLD", "int", None,
        "Multi-process world size.", _G)
declare("MXNET_TPU_RANK", "int", None,
        "This process's rank in the multi-process world.", _G)

_G = "launch"
declare("MXNET_LAUNCH_MAX_RESTARTS", "int", 3,
        "Supervised-launcher restart budget: whole-job relaunches "
        "after a worker death before giving up.", _G)
declare("MXNET_LAUNCH_BACKOFF", "float", 1.0,
        "First supervised-restart backoff, seconds (doubles per "
        "consecutive restart).", _G)
declare("MXNET_LAUNCH_GRACE", "float", 5.0,
        "Seconds between SIGTERM and SIGKILL when the launcher tears "
        "down surviving workers.", _G)
declare("MXNET_LAUNCH_ALLOW_SHRINK", "bool", False,
        "Supervised restart after a host loss may relaunch with N-1 "
        "workers (degraded) instead of a same-size replacement.", _G)
declare("MXNET_LAUNCH_RESTART", "int", 0,
        "Restart generation, set BY the supervisor in every worker's "
        "env (0 = first launch).", _G)
declare("MXNET_LAUNCH_RESUME_EPOCH", "int", None,
        "Last good manifest epoch, set BY the supervisor on restart "
        "so workers resume instead of starting fresh.", _G)
declare("MXNET_HB_DIR", "path", "",
        "Heartbeat directory of the launcher contract; workers "
        "touch per-rank files, the monitor detects stale peers.", _G)
declare("MXNET_HB_INTERVAL_MS", "int", 200,
        "Milliseconds between heartbeat-file touches.", _G)
declare("MXNET_HB_TIMEOUT_MS", "int", 2000,
        "Peer-heartbeat staleness that counts as a lost host "
        "(HostLostError + nonzero exit).", _G)

_G = "io"
declare("MXNET_DATA_PIPELINE", "bool", True,
        "Route Module/Gluon fit loops through the async input "
        "pipeline.", _G)
declare("MXNET_DATA_WORKERS", "int", 2,
        "Decode-pool width of the async input pipeline.", _G)
declare("MXNET_USE_NATIVE_IO", "bool", True,
        "Use the native record/image readers where available.", _G)
declare("MXNET_ASYNC_CHECKPOINT", "bool", True,
        "Write checkpoints from the bounded background writer "
        "instead of blocking the step.", _G)
declare("MXNET_CHECKPOINT_INFLIGHT", "int", 2,
        "Bounded queue depth of in-flight async checkpoint "
        "snapshots (backpressure past it).", _G)

_G = "serving"
declare("MXNET_SERVING_MAX_OUTSTANDING", "int", 2,
        "Per-replica outstanding-dispatch bound (admission "
        "backpressure).", _G)
declare("MXNET_SERVING_RECORD_EVERY", "int", 50,
        "Batches between serving telemetry records.", _G)
declare("MXNET_SERVING_LATENCY_RING", "int", 8192,
        "Ring size of the serving latency reservoir.", _G)
declare("MXNET_SERVING_PRIORITIES", "int", 3,
        "Number of admission priority classes (0 lowest .. N-1 "
        "highest); overload sheds the lowest class first.", _G)
declare("MXNET_KV_PAGE_SIZE", "int", 16,
        "Tokens per KV-cache page of the paged decode pool.", _G)
declare("MXNET_KV_POOL_PAGES", "int", 256,
        "Total pages in the decode KV-cache pool (page 0 is the "
        "reserved dump page).", _G)
declare("MXNET_KV_DTYPE", "str", "float32",
        "Storage dtype of the paged KV-cache pool: float32 | "
        "bfloat16 | int8 (int8 adds per-page scales and dequantizes "
        "on gather).", _G)
declare("MXNET_KV_PREFIX_CACHE", "bool", False,
        "Prefix-aware KV page sharing: completed prefills register "
        "their page-aligned token runs in a content-hashed index, a "
        "matching later prompt enters decode on the SHARED pages "
        "(refcounted, copy-on-write on first divergence) and "
        "computes only the un-cached suffix.", _G)
declare("MXNET_KV_MODEL_QUOTA", "int", 0,
        "Default per-model page quota when several DecodeServers "
        "share one KVCachePool (0 = no quota); an explicit "
        "pool_quota= on the server overrides it.", _G)
declare("MXNET_DECODE_WINDOW", "int", 8,
        "Concurrent decode slots of the continuous batcher (the "
        "decode step's fixed batch size).", _G)
declare("MXNET_DECODE_STOP_TIMEOUT_MS", "int", 5000,
        "Bound on DecodeServer.stop waiting for its scheduler thread; "
        "past it, outstanding streams fail with the typed "
        "ServerClosedError instead of hanging their consumers.", _G)

_G = "router"
declare("MXNET_ROUTER_PROBE_MS", "int", 50,
        "Milliseconds between fleet health-probe sweeps of the "
        "serving router.", _G)
declare("MXNET_ROUTER_STRIKES", "int", 2,
        "Consecutive failed probes before a replica is confirmed "
        "lost (two-strike false-positive guard).", _G)
declare("MXNET_ROUTER_MAX_INFLIGHT", "int", 8,
        "Per-replica bound on router-dispatched in-flight sessions "
        "(dispatch backpressure; excess sessions wait in the tenant "
        "queues where WFQ ordering applies).", _G)
declare("MXNET_ROUTER_TENANT_QUEUE", "int", 256,
        "Per-tenant router queue bound; past it the newest lowest-"
        "priority queued session of that tenant is shed.", _G)
declare("MXNET_ROUTER_TENANT_WEIGHT", "float", 1.0,
        "Default weighted-fair-queueing weight of a tenant not "
        "configured explicitly.", _G)
declare("MXNET_ROUTER_TENANT_RATE", "float", 0.0,
        "Default per-tenant token-bucket refill rate, tokens/sec "
        "(prompt + budgeted generation tokens count; 0 = "
        "unlimited).", _G)
declare("MXNET_ROUTER_TENANT_BURST", "float", 0.0,
        "Default per-tenant token-bucket capacity (0 = 2 x rate, or "
        "unlimited when the rate is 0).", _G)
declare("MXNET_ROUTER_DRAIN_TIMEOUT_MS", "int", 10000,
        "Graceful-drain budget per replica; sessions still streaming "
        "past it fail over to the remaining replicas instead of "
        "blocking the drain.", _G)
declare("MXNET_ROUTER_RECORD_EVERY", "int", 50,
        "Router pump rounds (with activity) between router telemetry "
        "records.", _G)
declare("MXNET_ROUTER_AUTOSCALE_IDLE_ROUNDS", "int", 500,
        "Consecutive idle health-sweep rounds before the autoscaler "
        "hook suggests scale_down to the supervisor callback.", _G)

_G = "bucketing"
declare("MXNET_BUCKET_LADDER", "str", "",
        "Process-default shape ladder: '8,16,32' or "
        "'4x16,8x16,8x32' (parsed by bucketing.ladder).", _G)
declare("MXNET_BUCKET_WINDOW", "int", None,
        "Ragged-stream reorder window, samples (default "
        "4 x batch_size).", _G)
declare("MXNET_BUCKETING_RECORD_EVERY", "int", 50,
        "Batches between bucketing telemetry records.", _G)

_G = "test"
declare("MXNET_TEST_SEED", "int", 0,
        "Deterministic seed for the test suite (0 = draw one and "
        "print it).", _G)
declare("MXNET_TEST_DEFAULT_CTX", "str", None,
        "Device context the test utilities bind to, e.g. 'cpu' or "
        "'tpu:0'.", _G)


# ---------------------------------------------------------------------------
# accessors
# ---------------------------------------------------------------------------

_UNSET = object()


def _var(name, kind):
    var = _REGISTRY.get(name)
    if var is None:
        raise MXNetError(
            "%s is not a registered environment variable — declare "
            "it in mxnet_tpu/envs.py (typed, with a default and a "
            "one-line doc)" % name)
    if var.kind != kind:
        raise MXNetError(
            "%s is declared as %s in mxnet_tpu/envs.py but was read "
            "as %s — use get_%s()" % (name, var.kind, kind, var.kind))
    return var


def _read(name, kind, default):
    var = _var(name, kind)
    raw = os.environ.get(name)
    if raw is None:
        return var.default if default is _UNSET else default
    return raw


def get_bool(name, default=_UNSET) -> Optional[bool]:
    """Strict boolean: 1/true/yes/on or 0/false/no/off (case-
    insensitive); VAR= (empty) means unset — the declared default,
    like every other accessor, so an empty value can never silently
    flip a default-ON gate off; anything else raises naming the
    variable."""
    raw = _read(name, "bool", default)
    if not isinstance(raw, str):
        return raw
    tok = raw.strip().lower()
    if not tok:
        return _unset_default(name, default)
    if tok in _TRUE:
        return True
    if tok in _FALSE:
        return False
    raise MXNetError(
        "%s=%r is not a boolean — use one of %s / %s"
        % (name, raw, "|".join(_TRUE), "|".join(_FALSE)))


def _unset_default(name, default):
    var = _REGISTRY[name]
    return var.default if default is _UNSET else default


def get_int(name, default=_UNSET) -> Optional[int]:
    raw = _read(name, "int", default)
    if not isinstance(raw, str):
        return raw
    if not raw.strip():
        # VAR= (empty) is the shell/compose idiom for "unset": it
        # means disabled/default everywhere in this tree, never a
        # parse error (get_bool's '' -> False is the same rule)
        return _unset_default(name, default)
    try:
        return int(raw.strip())
    except ValueError:
        raise MXNetError("%s=%r is not an integer" % (name, raw))


def get_float(name, default=_UNSET) -> Optional[float]:
    raw = _read(name, "float", default)
    if not isinstance(raw, str):
        return raw
    if not raw.strip():
        return _unset_default(name, default)
    try:
        return float(raw.strip())
    except ValueError:
        raise MXNetError("%s=%r is not a number" % (name, raw))


def get_str(name, default=_UNSET) -> Optional[str]:
    raw = _read(name, "str", default)
    return raw.strip() if isinstance(raw, str) else raw


def get_path(name, default=_UNSET) -> Optional[str]:
    """A filesystem path (no existence check — creation is the
    caller's policy); surrounding whitespace stripped."""
    raw = _read(name, "path", default)
    return raw.strip() if isinstance(raw, str) else raw


def get_raw(name) -> Optional[str]:
    """The unparsed value of a DECLARED variable (None when unset) —
    for knobs with their own grammar (``MXNET_BUCKET_LADDER``,
    ``MXNET_FAULT_PLAN``) whose parse lives next to their domain."""
    if name not in _REGISTRY:
        _var(name, "str")          # raises the not-registered error
    return os.environ.get(name)


def snapshot():
    """{name: raw value} for every DECLARED variable currently set in
    the process environment — the diagnose tool's knob table."""
    return {name: os.environ[name] for name in _REGISTRY
            if name in os.environ}


# ---------------------------------------------------------------------------
# generated reference
# ---------------------------------------------------------------------------

def render_reference():
    """The MXNET_* environment-variable reference as markdown, derived
    from the registry (``python -m mxnet_tpu.tools.lint --envs``)."""
    lines = ["# MXNET_* environment variables",
             "",
             "Generated from `mxnet_tpu/envs.py` by "
             "`python -m mxnet_tpu.tools.lint --envs` — do not edit "
             "by hand.", ""]
    groups = {}
    for var in _REGISTRY.values():
        groups.setdefault(var.group, []).append(var)
    for group, entries in groups.items():
        lines.append("## %s" % group)
        lines.append("")
        lines.append("| variable | type | default | description |")
        lines.append("|---|---|---|---|")
        for v in entries:
            default = "" if v.default is None else repr(v.default)
            lines.append("| `%s` | %s | `%s` | %s |"
                         % (v.name, v.kind, default, v.doc))
        lines.append("")
    return "\n".join(lines)
