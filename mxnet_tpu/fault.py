"""Fault-tolerance subsystem: deterministic fault injection, non-finite
gradient guards, and retrying synchronization wrappers.

The reference MXNet survived worker churn through ps-lite's server-side
state (SURVEY §5.8); here resilience is host-side and testable:

- **Fault injection** (:class:`FaultPlan`) — ``MXNET_FAULT_PLAN`` holds a
  ``;``-separated list of ``site:step=N:action[:count=K]`` entries, e.g.
  ``push:step=3:raise``, ``allreduce:step=7:hang``, ``grad:step=5:nan``.
  Injection points (:func:`inject`) are threaded through kvstore
  push/pull, the collective wrappers, ``engine.wait_for_all``, process
  group init, and the optimizer updater (site ``grad``). A site's step
  counter counts *visits* (for retried sites, attempts); an entry fires
  on visits ``step .. step+count-1`` (``count=inf`` fires forever). With
  ``MXNET_FAULT_PLAN`` unset every injection point is a no-op.

- **Non-finite gradient guard** (:func:`filter_gradient`) — policies
  ``skip_step`` (drop the update, count it in ``stats()``) and
  ``scale_backoff`` (additionally halve a dynamic loss scale, regrow it
  after ``MXNET_LOSS_SCALE_WINDOW`` clean steps). Selected with
  ``MXNET_NONFINITE_GUARD``; a plan containing a ``grad`` site enables
  ``skip_step`` automatically. Off (zero-cost) otherwise.

- **Retries** (:func:`with_retries`) — exponential backoff + jitter
  under a wall-clock deadline from ``MXNET_KVSTORE_TIMEOUT``; when the
  deadline passes, a typed :class:`CollectiveTimeoutError` is raised
  instead of hanging forever.

State is process-global; :func:`reset` re-reads the environment (tests
that monkeypatch ``MXNET_*`` vars must call it).

Telemetry unification: the exact branch points that advance
``stats()``'s skipped_steps/retries/timeouts also call
``telemetry.note(...)`` (lazy import, cold paths only), so an active
telemetry run's goodput accounting reconciles with :func:`stats` by
construction (README "Observability").
"""
from __future__ import annotations

import logging
import os
import random
import threading
import time

from . import envs
from .base import MXNetError

__all__ = ["FaultPlan", "InjectedFault", "InjectedHang",
           "CollectiveTimeoutError", "plan", "set_plan", "reset",
           "active", "is_enabled", "inject", "with_retries", "guard",
           "join_process_group", "filter_gradient", "guard_policy",
           "loss_scale", "stats", "reset_stats", "grad_poison",
           "fused_step_guard"]

_ACTIONS = ("raise", "hang", "stall", "nan", "inf")
# the wired injection points; a typo'd site would otherwise make a
# chaos run silently test nothing. ckpt_write/ckpt_fsync sit inside
# checkpoint.atomic_write_file so a planned fault can abort or stall a
# save at an exact file boundary (torn-write / slow-disk testing).
# serve_admit/serve_dispatch sit on the inference-serving request path
# (serving/server.py): admit fires per submitted request, dispatch per
# batcher pass — a planned hang at dispatch stalls batch formation so
# queued requests age past their deadlines (deterministic shed/timeout
# testing), a raise there is counted and survived, never fatal.
# serve_decode fires per continuous-batcher decode step and kv_evict
# per KV-cache page reclaim (serving/decode.py, serving/kvcache.py): a
# planned hang at serve_decode stalls token production so a streaming
# request ages past its deadline, proving its pages come back through
# the counted kv_evict reclaim path.
# kv_share fires once per prefix-index lookup of an admitted prompt
# and kv_cow once per copy-on-write page split (serving/kvcache.py,
# serving/decode.py): a planned raise at kv_share is a deterministic
# hash-collision-style MISS (the request pays a full private prefill),
# and a planned raise at kv_cow is counted and degrades the request to
# a private-copy re-prefill of everything it has computed so far —
# greedy decode makes the degraded stream token-identical, never a
# wrong token.
# serve_route/replica_lost are the fleet-router sites (serving/
# router.py, serving/fleet.py): serve_route fires once per router
# dispatch — a raise is counted and survived (the session stays queued
# and routes on the next pass), a hang stalls dispatch so queued
# sessions age deterministically; replica_lost fires once per replica
# per health sweep — a planned raise CONFIRMS the loss of the replica
# under probe on that exact visit, driving the failover/replay path
# without killing anything or racing a timing window.
# proc_hb/proc_join/proc_exit are the process-boundary sites of the
# multi-host story (parallel/multihost.py, tools/launch.py): proc_hb
# fires on every heartbeat-writer tick (stall/hang wedge the beat so
# PEERS detect the stale file; raise kills the beat outright),
# proc_join at process-group join, proc_exit once per training step on
# the training thread — `proc_exit:step=N:raise` is the deterministic
# "host dies at exactly step N" the supervised launcher's
# restart-the-world path is tested against.
# flightrec fires once per flight-recorder dump attempt (flightrec.py)
# — a planned raise proves a failing dumper is counted and swallowed,
# never fatal to the process it is post-morteming.
_SITES = ("push", "pull", "allreduce", "wait", "init", "grad",
          "ckpt_write", "ckpt_fsync", "serve_admit", "serve_dispatch",
          "serve_decode", "serve_route", "kv_evict", "kv_share",
          "kv_cow", "replica_lost",
          "proc_hb", "proc_join", "proc_exit", "flightrec")
# corruption needs a value to corrupt — only the grad site carries one
_VALUE_SITES = ("grad",)
_GUARD_POLICIES = ("skip_step", "scale_backoff")

_LOSS_SCALE_MAX = 2.0 ** 24


class InjectedFault(MXNetError):
    """A fault raised by a MXNET_FAULT_PLAN entry (action ``raise``)."""


class InjectedHang(InjectedFault):
    """A planned hang: the injection point blocked for
    MXNET_FAULT_HANG_SECONDS and then surfaced as a timed-out op."""


class CollectiveTimeoutError(MXNetError):
    """A synchronization op (kvstore push/pull, collective, barrier,
    process-group init) did not complete within MXNET_KVSTORE_TIMEOUT
    despite retries."""


class _PlanEntry:
    __slots__ = ("site", "step", "action", "count")

    def __init__(self, site, step, action, count):
        self.site, self.step = site, step
        self.action, self.count = action, count

    def fires(self, visit):
        return self.step <= visit < self.step + self.count

    def __repr__(self):
        spec = "%s:step=%d:%s" % (self.site, self.step, self.action)
        if self.count != 1:
            spec += ":count=%s" % ("inf" if self.count == float("inf")
                                   else int(self.count))
        return spec


def _parse_entry(text):
    parts = [p.strip() for p in text.split(":") if p.strip()]
    if len(parts) < 2:
        raise MXNetError(
            "fault plan entry %r: want site:step=N:action[:count=K]"
            % (text,))
    site, step, count, action = parts[0], 1, 1, None
    for tok in parts[1:]:
        if tok.startswith("step="):
            step = int(tok[len("step="):])
        elif tok.startswith("count="):
            val = tok[len("count="):]
            count = float("inf") if val in ("inf", "-1") else int(val)
        elif tok in _ACTIONS:
            action = tok
        else:
            raise MXNetError(
                "fault plan entry %r: unknown token %r (actions: %s)"
                % (text, tok, "|".join(_ACTIONS)))
    if action is None:
        raise MXNetError("fault plan entry %r: no action given" % (text,))
    if step < 1:
        raise MXNetError("fault plan entry %r: step is 1-based" % (text,))
    if site not in _SITES:
        raise MXNetError(
            "fault plan entry %r: unknown site %r (sites: %s)"
            % (text, site, "|".join(_SITES)))
    if action in ("nan", "inf") and site not in _VALUE_SITES:
        raise MXNetError(
            "fault plan entry %r: action %r only applies to value-"
            "carrying sites (%s)" % (text, action, "|".join(_VALUE_SITES)))
    return _PlanEntry(site, step, action, count)


class FaultPlan:
    """A parsed MXNET_FAULT_PLAN: entries plus per-site visit counters."""

    def __init__(self, entries):
        self.entries = list(entries)
        self._visits = {}

    @classmethod
    def parse(cls, spec):
        entries = [
            _parse_entry(e)
            for e in spec.replace(";", ",").split(",") if e.strip()]
        return cls(entries)

    def visit(self, site):
        """Count one visit to ``site``; return the entry that fires on
        this visit, or None."""
        n = self._visits.get(site, 0) + 1
        self._visits[site] = n
        for entry in self.entries:
            if entry.site == site and entry.fires(n):
                return entry
        return None

    def has_site(self, site):
        return any(e.site == site for e in self.entries)

    def __repr__(self):
        return "FaultPlan(%s)" % ";".join(repr(e) for e in self.entries)


# ---------------------------------------------------------------------------
# process-global state
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_plan: FaultPlan | None = None
_plan_loaded = False
_guard: str | None = None
_guard_loaded = False
_loss_scale_val: float | None = None
_good_steps = 0
_jitter_rng = random.Random(0)


def _fresh_stats():
    return {"skipped_steps": 0, "retries": 0, "timeouts": 0,
            "injected": {}, "resumed_from_epoch": None,
            "clean_resumes": 0, "rollback_resumes": 0,
            "rollback_epochs": 0}


_stats = _fresh_stats()


def plan():
    """The active FaultPlan, parsed once from MXNET_FAULT_PLAN (None
    when unset/empty)."""
    global _plan, _plan_loaded
    if not _plan_loaded:
        with _lock:
            if not _plan_loaded:
                spec = envs.get_raw("MXNET_FAULT_PLAN") or ""
                _plan = FaultPlan.parse(spec) if spec.strip() else None
                if _plan is not None and not _plan.entries:
                    _plan = None
                _plan_loaded = True
    return _plan


def _reset_guard_state_locked():
    """Clear guard runtime state (loss scale, regrow window, step
    tracking); caller holds _lock."""
    global _guard, _guard_loaded, _loss_scale_val, _good_steps
    global _seen_indices, _step_clean
    _guard, _guard_loaded = None, False
    _loss_scale_val, _good_steps = None, 0
    _seen_indices, _step_clean = set(), True


def set_plan(spec):
    """Install a plan programmatically (a spec string, a FaultPlan, or
    None); resets counters, guard resolution, and guard runtime state
    (loss scale, step tracking) so consecutive experiments start
    clean."""
    global _plan, _plan_loaded
    with _lock:
        if spec is None or isinstance(spec, FaultPlan):
            _plan = spec
        else:
            _plan = FaultPlan.parse(spec)
            if not _plan.entries:
                _plan = None
        _plan_loaded = True
        _reset_guard_state_locked()
    reset_stats()


def reset():
    """Forget cached plan/guard/scale state and re-read the environment
    on next use. Tests that monkeypatch MXNET_* vars call this."""
    global _plan, _plan_loaded, _retry_cfg
    with _lock:
        _plan, _plan_loaded = None, False
        _retry_cfg = None
        _reset_guard_state_locked()
    reset_stats()


def reset_stats():
    global _stats
    with _lock:
        _stats = _fresh_stats()


def active():
    """True when a fault plan is installed."""
    return plan() is not None


def guard_policy():
    """The resolved non-finite-guard policy: MXNET_NONFINITE_GUARD when
    set (``off`` disables), else ``skip_step`` when the active plan has
    a ``grad`` site, else None."""
    global _guard, _guard_loaded
    if not _guard_loaded:
        env = envs.get_str("MXNET_NONFINITE_GUARD")
        if env and env != "off":
            if env not in _GUARD_POLICIES:
                raise MXNetError(
                    "MXNET_NONFINITE_GUARD=%r (want %s|off)"
                    % (env, "|".join(_GUARD_POLICIES)))
            resolved = env
        elif env == "off":
            resolved = None
        else:
            p = plan()
            resolved = "skip_step" if p is not None and p.has_site("grad") \
                else None
        with _lock:
            _guard, _guard_loaded = resolved, True
    return _guard


def is_enabled():
    """Cheap hot-path check: any resilience feature (plan or guard) on?"""
    return active() or guard_policy() is not None


# ---------------------------------------------------------------------------
# injection
# ---------------------------------------------------------------------------

def _hang_seconds():
    return envs.get_float("MXNET_FAULT_HANG_SECONDS")


def _corrupt(value, kind):
    """A poisoned COPY of ``value`` — the caller's buffer is never
    touched, so an injected fault on an accumulating (grad_req='add')
    gradient clears with the next backward like a real transient."""
    import jax.numpy as jnp
    bad = float("nan") if kind == "nan" else float("inf")
    if hasattr(value, "copy") and hasattr(value, "asnumpy"):
        out = value.copy()                    # deep (sparse parts too)
        target = getattr(out, "_sp_data", out)
        target._set_data(jnp.full_like(target._data, bad))
        return out
    return jnp.full_like(value, bad)


def _visit_site(site):
    """Count one visit to ``site``; return the corruption entry firing
    on this visit (stats-accounted) or None. ``raise``/``hang`` entries
    fire here as exceptions."""
    p = plan()
    if p is None:
        return None
    with _lock:
        entry = p.visit(site)
        if entry is not None:
            _stats["injected"][site] = _stats["injected"].get(site, 0) + 1
    if entry is None:
        return None
    if entry.action == "raise":
        raise InjectedFault("planned fault at site %r (%r)" % (site, entry))
    if entry.action == "hang":
        time.sleep(_hang_seconds())
        raise InjectedHang(
            "planned hang at site %r (%r): blocked %.3fs"
            % (site, entry, _hang_seconds()))
    if entry.action == "stall":
        # a slow op, not a dead one: sleep MXNET_FAULT_HANG_SECONDS
        # and carry on — the deterministic "degraded but alive" case
        # (straggler devices, slow disks) the SLO watchdog's drift
        # detector is tested against
        time.sleep(_hang_seconds())
        return None
    return entry


def inject(site, value=None):
    """One injection point. Counts a visit to ``site``; when a plan
    entry fires: ``raise``→InjectedFault, ``hang``→bounded sleep then
    InjectedHang, ``stall``→the same bounded sleep but NO exception (a
    slow op, not a dead one), ``nan``/``inf``→return a corrupted copy
    of ``value``. Returns ``value`` (possibly corrupted) otherwise.
    No-op without an active plan."""
    entry = _visit_site(site)
    if entry is not None and value is not None:
        return _corrupt(value, entry.action)
    return value


def grad_poison():
    """Fused-step injection hook for the ``grad`` site: counts ONE
    visit (the fused executor calls it once per parameter per step,
    matching the eager updater's visit order) and returns the poison
    scalar the compiled step splices over that parameter's gradient —
    0.0 when nothing fires, nan/inf when a corruption entry does.
    ``raise``/``hang`` actions fire here, host-side, exactly like the
    eager path."""
    entry = _visit_site("grad")
    if entry is None:
        return 0.0
    return float("nan") if entry.action == "nan" else float("inf")


# ---------------------------------------------------------------------------
# retries
# ---------------------------------------------------------------------------

_retry_cfg = None


def _retry_config():
    """(timeout, backoff, max_backoff) from the environment, parsed
    once — with_retries sits on the per-key dist push path, so the env
    must not be re-read per call. reset() re-reads."""
    global _retry_cfg
    if _retry_cfg is None:
        _retry_cfg = (
            envs.get_float("MXNET_KVSTORE_TIMEOUT"),
            envs.get_float("MXNET_KVSTORE_RETRY_BACKOFF"),
            envs.get_float("MXNET_KVSTORE_RETRY_MAX_BACKOFF"))
    return _retry_cfg


def with_retries(fn, timeout=None, backoff=None, max_backoff=None,
                 retry_on=None, site=None):
    """Run ``fn()`` with exponential backoff + jitter under a wall-clock
    deadline; raise :class:`CollectiveTimeoutError` (chaining the last
    error) once the deadline passes.

    The deadline is enforced BETWEEN attempts: a planned ``hang`` is
    bounded (it sleeps MXNET_FAULT_HANG_SECONDS then raises), but an op
    genuinely wedged inside the runtime cannot be preempted from this
    thread — pair with an external watchdog for that class of failure.

    - ``timeout``: seconds; default MXNET_KVSTORE_TIMEOUT (60).
    - ``backoff``: first retry delay; default
      MXNET_KVSTORE_RETRY_BACKOFF (0.05), doubling per attempt up to
      ``max_backoff`` (MXNET_KVSTORE_RETRY_MAX_BACKOFF, 2.0).
    - ``retry_on``: exception classes worth retrying; defaults to
      injected faults plus transient transport errors
      (ConnectionError/TimeoutError/OSError).
    - ``site``: optional injection site visited before each attempt, so
      planned faults exercise the retry path itself.
    """
    env_timeout, env_backoff, env_max_backoff = _retry_config()
    if timeout is None:
        timeout = env_timeout
    if backoff is None:
        backoff = env_backoff
    if max_backoff is None:
        max_backoff = env_max_backoff
    if retry_on is None:
        retry_on = (InjectedFault, ConnectionError, TimeoutError, OSError)
    deadline = time.monotonic() + timeout
    attempt = 0
    while True:
        try:
            if site is not None:
                inject(site)
            return fn()
        except CollectiveTimeoutError:
            raise
        except retry_on as exc:
            now = time.monotonic()
            if now >= deadline:
                with _lock:
                    _stats["timeouts"] += 1
                from . import telemetry
                telemetry.note("timeouts")
                raise CollectiveTimeoutError(
                    "%s did not complete within %.3fs (%d attempt(s); "
                    "last error %s: %s)"
                    % (site or getattr(fn, "__name__", "op"), timeout,
                       attempt + 1, type(exc).__name__, exc)) from exc
            # jitter BEFORE the deadline clamp so the sleep can never
            # overshoot the promised wall-clock bound
            delay = min(backoff * (2.0 ** attempt), max_backoff)
            delay *= 1.0 + 0.1 * _jitter_rng.random()
            delay = min(delay, max(deadline - now, 0.0))
            with _lock:
                _stats["retries"] += 1
            from . import telemetry
            telemetry.note("retries")
            time.sleep(delay)
            attempt += 1


def guard(fn, site):
    """The shared fast-path gate for sync points: ``with_retries`` when
    a fault plan is active, a plain direct call otherwise — so inactive
    runs pay neither injection accounting nor deadline bookkeeping."""
    if active():
        return with_retries(fn, site=site)
    return fn()


def join_process_group():
    """Join the process group described by the launcher's DMLC_* env
    contract (tools/launch.py; ref dmlc tracker env in
    python/mxnet/kvstore_server.py), retrying transient coordinator
    races under the kvstore deadline. No-op without a contract; an
    already-joined process surfaces as RuntimeError and is left alone.
    Shared by package import (pre-backend-init) and kvstore creation."""
    import os
    n = int(os.environ.get("DMLC_NUM_WORKER", "1") or 1)
    if n <= 1 or "DMLC_WORKER_ID" not in os.environ:
        return
    import jax
    inject("proc_join")
    try:
        with_retries(
            lambda: jax.distributed.initialize(
                coordinator_address="%s:%s" % (
                    os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
                    os.environ.get("DMLC_PS_ROOT_PORT", "9091")),
                num_processes=n,
                process_id=int(os.environ["DMLC_WORKER_ID"])),
            retry_on=(ConnectionError, OSError, InjectedFault),
            site="init")
    except RuntimeError:
        pass          # already initialized
    # the launcher contract's failure-detection side: a heartbeat
    # writer + peer monitor per process (MXNET_HB_DIR — set by
    # `tools/launch.py --supervise`; no-op without it)
    from .parallel import multihost
    multihost.maybe_start_heartbeat()


# ---------------------------------------------------------------------------
# non-finite gradient guard
# ---------------------------------------------------------------------------

def _all_finite(grad):
    import jax.numpy as jnp
    x = getattr(grad, "_sp_data", None)
    if x is None:
        x = grad
    data = x._data if hasattr(x, "_data") else x
    return bool(jnp.isfinite(data).all())


def loss_scale():
    """Current dynamic loss scale (scale_backoff policy); 1.0 when that
    policy is off. The training loop multiplies the loss by this before
    backward; gluon ``Trainer.step`` divides it back out of the update."""
    global _loss_scale_val
    if guard_policy() != "scale_backoff":
        return 1.0
    if _loss_scale_val is None:
        _loss_scale_val = envs.get_float("MXNET_LOSS_SCALE")
    return _loss_scale_val


def _emit_scale_record(prev, cur, cause):
    """One ``loss_scale`` telemetry record per scale CHANGE — the
    trajectory ``tools.diagnose`` renders (a healthy AMP run shows a
    few early backoffs then a slow regrow staircase; a run whose scale
    pins at 1.0 has a numerics problem, not an overflow problem)."""
    from . import telemetry
    telemetry.external_record({"type": "loss_scale", "prev": prev,
                               "scale": cur, "cause": cause})


def _backoff_scale():
    global _loss_scale_val, _good_steps
    prev = loss_scale()
    _loss_scale_val = max(prev * 0.5, 1.0)
    _good_steps = 0
    if _loss_scale_val != prev:
        _emit_scale_record(prev, _loss_scale_val, "backoff")
    return prev, _loss_scale_val


# The updater runs once per parameter index per optimizer step; the
# guard's accounting is per STEP (one halving / one skipped_steps count
# no matter how many of the step's gradients overflowed). A repeating
# index marks the next step's first update.
_seen_indices: set = set()
_step_clean = True


def _close_step():
    """End-of-step accounting: a fully clean step advances the regrow
    window (scale_backoff); a bad step already halved on its first
    non-finite gradient."""
    global _loss_scale_val, _good_steps
    if guard_policy() != "scale_backoff" or not _step_clean:
        return
    _good_steps += 1
    window = envs.get_int("MXNET_LOSS_SCALE_WINDOW")
    if _good_steps >= window:
        prev = loss_scale()
        _loss_scale_val = min(prev * 2.0, _LOSS_SCALE_MAX)
        _good_steps = 0
        if _loss_scale_val != prev:
            _emit_scale_record(prev, _loss_scale_val, "regrow")


def _note_step_boundary(index):
    global _seen_indices, _step_clean
    if index in _seen_indices:
        _close_step()
        _seen_indices = set()
        _step_clean = True
    _seen_indices.add(index)


def filter_gradient(index, grad):
    """The optimizer-updater guard: apply any planned ``grad`` fault,
    then test finiteness under the active policy. Returns
    ``(grad, skip)``; ``skip=True`` means drop this parameter's update.
    stats()['skipped_steps'] and the scale_backoff halving advance once
    per optimizer step, however many of its gradients overflowed."""
    grad = inject("grad", value=grad)
    policy = guard_policy()
    if policy is None:
        return grad, False
    _note_step_boundary(index)
    if _all_finite(grad):
        return grad, False
    global _step_clean
    first_bad = _step_clean
    _step_clean = False
    if first_bad:
        with _lock:
            _stats["skipped_steps"] += 1
        from . import telemetry
        telemetry.note("skipped_steps")
        if policy == "scale_backoff":
            prev, cur = _backoff_scale()
            logging.warning(
                "fault: non-finite gradient for index %s — skipping "
                "update, loss scale %g -> %g", index, prev, cur)
        else:
            logging.warning(
                "fault: non-finite gradient for index %s — skipping "
                "update (policy=skip_step)", index)
    return grad, True


def fused_step_guard(all_finite):
    """Per-step guard accounting for the compiled fused step. The skip
    itself happened INSIDE the program (a ``jnp.where`` kept the old
    weight and state for every non-finite gradient); this mirrors
    :func:`filter_gradient`'s host bookkeeping — one skipped_steps
    count / one scale halving per bad step, regrow-window advance per
    clean step. No-op when no guard policy is active."""
    global _step_clean
    policy = guard_policy()
    if policy is None:
        return
    if all_finite:
        _step_clean = True
        _close_step()
        return
    # mark the step dirty so interleaved eager bookkeeping
    # (_note_step_boundary -> _close_step) cannot count this overflowed
    # step toward the scale-regrow window
    _step_clean = False
    with _lock:
        _stats["skipped_steps"] += 1
    from . import telemetry
    telemetry.note("skipped_steps")
    if policy == "scale_backoff":
        prev, cur = _backoff_scale()
        logging.warning(
            "fault: non-finite gradient inside fused step — update "
            "dropped in-program, loss scale %g -> %g", prev, cur)
    else:
        logging.warning(
            "fault: non-finite gradient inside fused step — update "
            "dropped in-program (policy=skip_step)")


# ---------------------------------------------------------------------------
# stats
# ---------------------------------------------------------------------------

def note_resume(epoch, skipped_epochs=0):
    """Record a checkpoint resume. ``skipped_epochs`` counts newer
    epochs the scan rejected (torn shards, corrupt params or corrupt
    sibling optimizer state) before settling on ``epoch`` — a
    *rollback* resume loses their steps; a clean resume loses none.
    tools.diagnose reconciles the rollback against the run's goodput."""
    skipped_epochs = int(skipped_epochs)
    with _lock:
        _stats["resumed_from_epoch"] = epoch
        if skipped_epochs > 0:
            _stats["rollback_resumes"] += 1
            _stats["rollback_epochs"] += skipped_epochs
        else:
            _stats["clean_resumes"] += 1
    if skipped_epochs > 0:
        from . import telemetry
        telemetry.note("resume_rollback_epochs", skipped_epochs)
        # the epoch training actually restarts from — the run's meta
        # begin_epoch was recorded before the resume bumped it, so
        # diagnose needs this to compute the epochs really trained
        telemetry.note("resume_next_epoch", int(epoch) + 1)


def stats():
    """Queryable resilience counters: skipped_steps, retries, timeouts,
    per-site injected counts, resumed_from_epoch, loss_scale,
    guard_policy."""
    with _lock:
        out = dict(_stats)
        out["injected"] = dict(_stats["injected"])
    out["loss_scale"] = loss_scale()
    out["guard_policy"] = guard_policy()
    return out
