"""End-to-end request/step tracing with Perfetto-loadable export —
the live, per-event half of the observability stack (the reference
framework's ``MXNET_PROFILER_*`` chrome://tracing dumps, grown to
cover causality across threads and subsystems).

The telemetry layer (PR 3) aggregates: phase totals, percentiles,
counters — you learn *how much*, never *which one*. This module
records *events*: every serving request gets a trace id at
``InferenceServer.submit`` and causally-linked spans across its whole
lifetime (queue wait → batch formation → replica dispatch → pad →
device compute → slice/respond), and every training step gets a step
span with its phase spans nested inside — now *including* the
off-thread work telemetry's exclusive-phase accounting deliberately
excludes: async-input-pipeline decode and H2D placement, and the
checkpoint writer's durable saves, each parented to the step that
triggered them via an explicit context token captured on the
triggering thread (:func:`context`), never via thread identity.
Compile events (``compile_watch``) and gradient-sync bucket events
(``parallel/grad_sync``) land as duration/instant events on their own
tracks.

Storage is a bounded ring (``MXNET_TRACE_RING`` events, default
200000): a week-long run keeps the most recent window, and
:func:`stats` reports how many events the bound dropped.
:func:`export` writes the ring as Chrome trace-event JSON
(``{"traceEvents": [...]}``) loadable in Perfetto / chrome://tracing —
``X`` complete events nest by time containment per track, serving
requests each get their own named synthetic track, and the write is
atomic (tmp + ``os.replace``).

Always cheap when off — the telemetry discipline: every hook is one
module-global ``None`` check, and :func:`span` returns a shared no-op
singleton (zero allocation). Enable with ``MXNET_TRACE=1`` (picked up
at ``telemetry.start``) or explicitly via :func:`enable`; set
``MXNET_TRACE_FILE`` to auto-export at ``disable``/atexit.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from . import envs

__all__ = ["enabled", "enable", "disable", "reset", "maybe_enable",
           "now", "add", "instant", "span", "context", "track",
           "export", "stats", "wire_context", "adopt_context",
           "merge_exports"]

_tracer = None          # the active _Trace; module-global None check
_lock = threading.Lock()


class _Trace:
    """One tracing session's ring + track table. Event appends run
    under the module lock (producers live on many threads)."""

    def __init__(self):
        self.t0 = time.perf_counter()
        self.t0_wall = time.time()
        self.events = deque(
            maxlen=max(1, envs.get_int("MXNET_TRACE_RING")))
        self.dropped = 0
        self.pid = os.getpid()
        # synthetic tracks (per-request, compile, grad_sync, ...) get
        # small ids; real threads use their ident — the two ranges
        # cannot collide in practice (thread idents are pointers).
        # The table is BOUNDED (MXNET_TRACE_TRACKS) with LRU
        # eviction: a long-lived traced server mints one track per
        # request, and the most-recently-USED labels win — hot
        # system tracks stay named while cold one-shot per-request
        # labels age out; events whose label was evicted (and whose
        # spans have usually rotated out of the ring anyway) export
        # under their bare numeric tid
        self.tracks = {}          # label -> tid (insertion-ordered)
        self.max_tracks = max(
            16, envs.get_int("MXNET_TRACE_TRACKS"))
        self.next_tid = 1
        # clock-offset samples recorded by adopt_context (bounded):
        # each pairs a peer's wall stamp with ours, so merge_exports
        # and diagnose can cross-check the wall-anchor alignment
        self.wire_samples = deque(maxlen=64)


class _NullSpan:
    """Shared no-op span — the whole cost of :func:`span` when tracing
    is off. Zero allocation: one module-level singleton."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL = _NullSpan()


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def enabled():
    """True while tracing is active."""
    return _tracer is not None


def enable():
    """Turn tracing on (idempotent). Returns the tracer."""
    global _tracer, _atexit_registered
    with _lock:
        if _tracer is None:
            _tracer = _Trace()
    if not _atexit_registered:
        _atexit_registered = True
        import atexit
        atexit.register(_atexit_export)
    return _tracer


_atexit_registered = False


def _atexit_export():
    """Export to MXNET_TRACE_FILE at interpreter exit for runs that
    never call disable()/export() themselves."""
    fname = envs.get_path("MXNET_TRACE_FILE")
    if _tracer is not None and fname:
        try:
            export(fname)
        except OSError:
            pass


def disable():
    """Turn tracing off. When ``MXNET_TRACE_FILE`` is set the ring is
    exported there first. Returns the export path (or None)."""
    global _tracer
    fname = envs.get_path("MXNET_TRACE_FILE") or None
    out = None
    if _tracer is not None and fname:
        try:
            out = export(fname)
        except OSError:
            out = None
    with _lock:
        _tracer = None
    return out


def reset():
    """Forget the tracer entirely (tests)."""
    global _tracer
    with _lock:
        _tracer = None


def maybe_enable():
    """Enable when the environment asks (``MXNET_TRACE=1`` or
    ``MXNET_TRACE_FILE`` set) — called from ``telemetry.start`` so
    tracing rides a run the way the compile watch does. Returns True
    when active after the call."""
    if _tracer is not None:
        return True
    on = envs.get_bool("MXNET_TRACE")
    if on or envs.get_path("MXNET_TRACE_FILE"):
        enable()
        return True
    return False


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------

def now():
    """The tracer's clock (``time.perf_counter`` — the same clock
    telemetry stamps with, so step/phase/trace timestamps agree)."""
    return time.perf_counter()


def track(label):
    """The synthetic track (Chrome ``tid``) named ``label``; the name
    is attached at export as a ``thread_name`` metadata event so
    Perfetto shows the label. The label table is bounded at
    ``MXNET_TRACE_TRACKS`` with LRU eviction — the most-recently-used
    labels keep their names (perpetually-hot system tracks stay
    resident; cold one-shot per-request labels age out, mirroring the
    event ring's newest-wins bound); an evicted label's events (if
    any still survive in the ring) export under a bare numeric tid,
    with their args (request ids etc.) still carrying the identity.
    None when tracing is off."""
    t = _tracer
    if t is None:
        return None
    with _lock:
        tid = t.tracks.pop(label, None)
        if tid is None:
            if len(t.tracks) >= t.max_tracks:
                # LRU evict: the pop/re-insert below refreshes every
                # hit, so perpetually-hot system tracks (compile,
                # grad_sync, io:*) stay resident while cold one-shot
                # per-request labels age out
                del t.tracks[next(iter(t.tracks))]
            tid = t.next_tid
            t.next_tid += 1
        t.tracks[label] = tid          # (re-)insert at the MRU end
        return tid


def _append_locked(t, ev):
    """Ring append; caller holds the lock. A full ring drops the
    OLDEST event (deque maxlen) and counts the drop."""
    if len(t.events) == t.events.maxlen:
        t.dropped += 1
    t.events.append(ev)


def _append(t, ev):
    with _lock:
        _append_locked(t, ev)


def add(name, cat, t_start, dur_s, tid=None, args=None):
    """Record one complete (``X``) event: ``t_start`` is a
    :func:`now` stamp, ``dur_s`` seconds. ``tid`` is a real thread
    ident or a :func:`track` id (default: the calling thread). No-op
    when tracing is off."""
    t = _tracer
    if t is None:
        return
    ev = {"name": name, "cat": cat, "ph": "X",
          "ts": round((t_start - t.t0) * 1e6, 3),
          "dur": round(max(dur_s, 0.0) * 1e6, 3),
          "pid": t.pid,
          "tid": tid if tid is not None else threading.get_ident()}
    if args:
        ev["args"] = args
    _append(t, ev)


def instant(name, cat, tid=None, args=None, t_at=None):
    """Record one instant (``i``) event at ``t_at`` (default now)."""
    t = _tracer
    if t is None:
        return
    ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
          "ts": round(((t_at if t_at is not None
                        else time.perf_counter()) - t.t0) * 1e6, 3),
          "pid": t.pid,
          "tid": tid if tid is not None else threading.get_ident()}
    if args:
        ev["args"] = args
    _append(t, ev)


class _Span:
    __slots__ = ("name", "cat", "tid", "args", "t0")

    def __init__(self, name, cat, tid, args):
        self.name = name
        self.cat = cat
        self.tid = tid
        self.args = args

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *a):
        add(self.name, self.cat, self.t0,
            time.perf_counter() - self.t0, tid=self.tid,
            args=self.args)
        return False


def span(name, cat="span", tid=None, args=None):
    """A context manager recording one ``X`` event around its body.
    The shared no-op singleton when tracing is off."""
    if _tracer is None:
        return _NULL
    return _Span(name, cat, tid, args)


def context():
    """The current causal context, captured ON THE TRIGGERING THREAD
    and passed to off-thread work (checkpoint writer, decode pool) so
    its spans are parented to the step that triggered them by an
    explicit token, never by thread identity. Returns ``{"step": N}``
    (N = the open/most recent telemetry step) or None when tracing is
    off / no run is active."""
    if _tracer is None:
        return None
    from . import telemetry
    run = telemetry._run
    if run is None:
        return None
    # the step this work will CLOSE under: run.steps counts closed
    # steps, and both step_begin/step_end mode (the open step) and
    # gluon tick mode (everything between boundaries closes at the
    # next tick) resolve to steps + 1. Advisory read, no lock — the
    # token is trace metadata, not accounting.
    return {"step": run.steps + 1}


# ---------------------------------------------------------------------------
# cross-process correlation (the wire context)
# ---------------------------------------------------------------------------

def process_identity():
    """This process's fleet identity: ``{"rank", "gen"}`` — the
    launcher-contract rank (DMLC_WORKER_ID, else MXNET_TPU_RANK, else
    0) and the supervisor restart generation (MXNET_LAUNCH_RESTART).
    Cheap enough for per-dispatch use; shared by the wire context,
    the flight recorder, and the /metrics identity gauge."""
    if "DMLC_WORKER_ID" in os.environ:
        try:
            rank = int(os.environ["DMLC_WORKER_ID"])
        except ValueError:
            rank = 0
    else:
        rank = envs.get_int("MXNET_TPU_RANK") or 0
    return {"rank": rank, "gen": envs.get_int("MXNET_LAUNCH_RESTART")}


def wire_context(**fields):
    """A serializable trace context for crossing a process boundary
    (router→replica dispatch, rank→rank multihost exchange): the
    sender's pid/rank/restart-generation identity, a paired
    wall+monotonic clock sample (so the receiver — and later
    :func:`merge_exports` — can align the two processes' trace
    clocks), and any caller identity ``fields`` (``request_id``,
    ``tenant``, ``step``). Plain JSON-safe dict. None when tracing is
    off or ``MXNET_TRACE_WIRE=0`` — callers forward it unconditionally
    and receivers treat None as "no context" (one None check)."""
    t = _tracer
    if t is None or not envs.get_bool("MXNET_TRACE_WIRE"):
        return None
    ident = process_identity()
    ctx = {"v": 1, "pid": t.pid, "rank": ident["rank"],
           "gen": ident["gen"], "wall": time.time(),
           "mono": time.perf_counter()}
    step = context()
    if step is not None:
        ctx["step"] = step["step"]
    ctx.update(fields)
    return ctx


# the wire-context keys that are transport plumbing, not identity —
# adopt_context strips these from the span-args view it returns
_WIRE_CLOCK_KEYS = ("v", "wall", "mono")


def adopt_context(ctx, name="ctx:adopt", cat="wire", tid=None):
    """Adopt a peer's :func:`wire_context` on the receiving side:
    records one ``i`` event carrying the peer identity plus the
    observed wall skew, stores a bounded clock-offset sample for
    export, and returns the identity args (``request_id``/``tenant``/
    ``origin_pid``/``origin_rank``/``gen``/``step``) for the receiver
    to stamp onto its own spans so the two processes' events join
    under one id. None (and no event) when tracing is off or ``ctx``
    is falsy."""
    t = _tracer
    if t is None or not ctx:
        return None
    wall_in = time.time()
    args = {"origin_pid": ctx.get("pid"),
            "origin_rank": ctx.get("rank")}
    for k, v in ctx.items():
        if k not in _WIRE_CLOCK_KEYS and k not in ("pid", "rank"):
            args[k] = v
    wall_out = ctx.get("wall")
    if isinstance(wall_out, (int, float)):
        # one-way wall delta: ≥ transit time when the hosts' wall
        # clocks agree; merge_exports uses the samples to report how
        # trustworthy the wall-anchor alignment is
        skew = wall_in - wall_out
        args["wall_skew_ms"] = round(skew * 1e3, 3)
        with _lock:
            t.wire_samples.append(
                {"origin_pid": ctx.get("pid"),
                 "origin_rank": ctx.get("rank"),
                 "wall_out": wall_out, "wall_in": wall_in})
    instant(name, cat, tid=tid, args=args)
    return args


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def stats():
    """{"events", "dropped", "tracks"} of the live ring; None when
    tracing is off."""
    t = _tracer
    if t is None:
        return None
    with _lock:
        return {"events": len(t.events), "dropped": t.dropped,
                "tracks": len(t.tracks)}


def export(path=None):
    """Export the ring as Chrome trace-event JSON. With ``path``,
    write atomically (tmp + ``os.replace``) and return the path;
    without, return the trace dict. Loadable in Perfetto
    (https://ui.perfetto.dev) and chrome://tracing. Raises
    RuntimeError when tracing was never enabled."""
    t = _tracer
    if t is None:
        raise RuntimeError("tracing.export: tracing is not enabled")
    with _lock:
        # track-name metadata is synthesized from the label table at
        # export time, NOT stored in the ring — a week-long run whose
        # ring rotated a million times still exports every surviving
        # event under a named track
        names = [{"name": "thread_name", "ph": "M", "pid": t.pid,
                  "tid": tid, "args": {"name": label}}
                 for label, tid in sorted(t.tracks.items(),
                                          key=lambda kv: kv[1])]
        events = names + list(t.events)
        dropped = t.dropped
        ident = process_identity()
        meta = {"pid": t.pid, "trace_t0_wall": t.t0_wall,
                "dropped_events": dropped,
                "rank": ident["rank"], "gen": ident["gen"]}
        if t.wire_samples:
            meta["wire_samples"] = list(t.wire_samples)
    trace = {"traceEvents": events, "displayTimeUnit": "ms",
             "otherData": meta}
    if path is None:
        return trace
    tmp = "%s.%d.tmp" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(trace, f)
    os.replace(tmp, path)
    return path


def merge_exports(inputs, path=None):
    """Clock-align N per-process Chrome-JSON exports into ONE
    Perfetto-loadable trace. ``inputs`` is a list of export paths (or
    already-loaded trace dicts). Pure offline function — works with
    tracing off.

    Alignment uses each export's ``otherData.trace_t0_wall`` anchor
    (every process stamped its monotonic t0 against the wall clock at
    enable): the earliest anchor becomes the merged t=0 and every
    other process's events are shifted by its anchor delta, so a
    request's router-side and replica-side spans nest causally on the
    shared timeline. Colliding pids (two processes on different hosts
    can share one) are remapped, each process track gets a
    ``process_name`` metadata row (``rank R gen G (pid P)``), and
    ``otherData.processes`` records the per-input anchor, shift, and
    any ``wire_samples`` (adopt-time clock-offset observations) so a
    reader can judge the alignment's trust. With ``path`` the merged
    trace is written atomically and the path returned; without, the
    merged dict is returned. Raises ValueError on empty input or an
    input with no ``trace_t0_wall`` anchor."""
    traces = []
    for src in inputs:
        if isinstance(src, dict):
            traces.append((str(src.get("otherData", {}).get("pid")),
                           src))
        else:
            with open(src) as f:
                traces.append((str(src), json.load(f)))
    if not traces:
        raise ValueError("merge_exports: no inputs")
    anchors = []
    for label, tr in traces:
        meta = tr.get("otherData") or {}
        t0 = meta.get("trace_t0_wall")
        if not isinstance(t0, (int, float)):
            raise ValueError(
                "merge_exports: input %s has no trace_t0_wall anchor "
                "(not a tracing.export file?)" % label)
        anchors.append(float(t0))
    base = min(anchors)
    used_pids = set()
    meta_events, span_events = [], []
    processes, dropped = [], 0
    for (label, tr), t0 in zip(traces, anchors):
        meta = tr.get("otherData") or {}
        orig_pid = meta.get("pid")
        pid = orig_pid if isinstance(orig_pid, int) else 0
        while pid in used_pids:        # same pid on two hosts
            pid += 1 << 20
        used_pids.add(pid)
        shift_us = (t0 - base) * 1e6
        for ev in tr.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = round(ev["ts"] + shift_us, 3)
            (meta_events if ev.get("ph") == "M"
             else span_events).append(ev)
        pname = "rank %s gen %s (pid %s)" % (
            meta.get("rank", "?"), meta.get("gen", 0), orig_pid)
        meta_events.append({"name": "process_name", "ph": "M",
                            "pid": pid, "args": {"name": pname}})
        dropped += int(meta.get("dropped_events", 0) or 0)
        processes.append({"pid": pid, "orig_pid": orig_pid,
                          "rank": meta.get("rank"),
                          "gen": meta.get("gen"),
                          "trace_t0_wall": t0,
                          "shift_us": round(shift_us, 3),
                          "wire_samples": meta.get("wire_samples",
                                                   [])})
    span_events.sort(key=lambda e: e.get("ts", 0.0))
    trace = {"traceEvents": meta_events + span_events,
             "displayTimeUnit": "ms",
             "otherData": {"merged_from": len(traces),
                           "trace_t0_wall": base,
                           "dropped_events": dropped,
                           "processes": processes}}
    if path is None:
        return trace
    tmp = "%s.%d.tmp" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(trace, f)
    os.replace(tmp, path)
    return path
