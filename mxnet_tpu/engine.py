"""Execution engine shim.

Reference: src/engine/ (ThreadedEnginePerDevice and friends) +
python/mxnet/engine.py. On TPU, op ordering and async dispatch are
provided by JAX/XLA: every dispatched computation returns a
future-backed array and XLA serializes device work per stream, which is
exactly the ordering guarantee the reference's Var read/write dependency
tracking provides for single-stream programs. What remains host-side:

- ``NaiveEngine`` ≙ ``jax.disable_jit()`` (synchronous debug mode,
  selected with MXNET_ENGINE_TYPE like the reference, engine.cc:33).
- bulking context managers (engine.h set_bulk_size) are accepted and
  no-op: whole-graph jit already executes fused programs.
- ``wait_for_all`` / per-array ``wait_to_read`` are the sync points.
"""
from __future__ import annotations

import contextlib

from .base import get_env

__all__ = ["bulk", "set_bulk_size", "wait_for_all", "engine_type",
           "naive_engine"]

_bulk_size = 15


def engine_type():
    return get_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")


def set_bulk_size(size):
    global _bulk_size
    prev = _bulk_size
    _bulk_size = size
    return prev


@contextlib.contextmanager
def bulk(size):
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)


def wait_for_all():
    from .ndarray import waitall
    waitall()


@contextlib.contextmanager
def naive_engine():
    """Synchronous, uncompiled execution for debugging (NaiveEngine)."""
    import jax
    with jax.disable_jit():
        yield
