"""Execution engine shim.

Reference: src/engine/ (ThreadedEnginePerDevice and friends) +
python/mxnet/engine.py. On TPU, op ordering and async dispatch are
provided by JAX/XLA: every dispatched computation returns a
future-backed array and XLA serializes device work per stream, which is
exactly the ordering guarantee the reference's Var read/write dependency
tracking provides for single-stream programs. What remains host-side:

- ``NaiveEngine`` ≙ ``jax.disable_jit()`` (synchronous debug mode,
  selected with MXNET_ENGINE_TYPE like the reference, engine.cc:33).
- bulking context managers (engine.h set_bulk_size) are accepted and
  no-op: whole-graph jit already executes fused programs.
- ``wait_for_all`` / per-array ``wait_to_read`` are the sync points.
"""
from __future__ import annotations

import contextlib

from . import envs

__all__ = ["bulk", "set_bulk_size", "wait_for_all", "engine_type",
           "naive_engine", "compiler_options"]

_bulk_size = 15
_compiler_options = None


def compiler_options(ctx=None):
    """Default XLA compile options for the framework's jitted programs.

    On TPU the latency-hiding scheduler overlaps the while-loop's
    cross-memory-space prefetches with compute (a measured ~3% on the
    ResNet-50 train step); other backends get no extra options — the
    options are TPU-only compile options, so callers that may compile
    for CPU (mixed-device processes, the op-level eager jits) must pass
    their target ``ctx`` or skip the options. Override with
    MXNET_XLA_COMPILER_OPTIONS="k=v,k2=v2" or disable with
    MXNET_XLA_COMPILER_OPTIONS=none (the reference's engine knobs are
    env-driven the same way, docs/faq/env_var.md).
    """
    global _compiler_options
    if _compiler_options is None:
        env = envs.get_str("MXNET_XLA_COMPILER_OPTIONS")
        if env == "none":
            _compiler_options = {}
        elif env:
            # explicit user options: applied verbatim on every backend
            _compiler_options = dict(kv.split("=", 1)
                                     for kv in env.split(",") if "=" in kv)
            _compiler_options["__from_env__"] = True
        else:
            _compiler_options = {
                "xla_tpu_enable_latency_hiding_scheduler": "true"}
    if not _compiler_options:
        return None
    if _compiler_options.get("__from_env__"):
        return {k: v for k, v in _compiler_options.items()
                if k != "__from_env__"}
    # the built-in default is a TPU-only option: gate on the target ctx
    # (mixed-device processes) and on a TPU actually being present
    try:
        import jax
        if ctx is not None and getattr(ctx, "device_type", None):
            if not str(ctx.device_type).startswith(("tpu", "gpu")):
                return None
        if not any(d.platform in ("tpu", "axon") or "TPU" in d.device_kind
                   for d in jax.devices()):
            return None
    except Exception:
        return None
    return _compiler_options


def engine_type():
    return envs.get_str("MXNET_ENGINE_TYPE")


def set_bulk_size(size):
    global _bulk_size
    prev = _bulk_size
    _bulk_size = size
    return prev


@contextlib.contextmanager
def bulk(size):
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)


def wait_for_all():
    from .ndarray import waitall
    from . import fault
    # faultable sync point: a planned hang here surfaces as a typed
    # CollectiveTimeoutError after MXNET_KVSTORE_TIMEOUT instead of
    # wedging the host thread (site "wait" in MXNET_FAULT_PLAN)
    return fault.guard(waitall, "wait")


@contextlib.contextmanager
def naive_engine():
    """Synchronous, uncompiled execution for debugging (NaiveEngine)."""
    import jax
    with jax.disable_jit():
        yield
