"""Contrib IO (parity: python/mxnet/contrib/io.py): wrap a Gluon
DataLoader as a classic ``DataIter`` so the Module API can consume
Gluon data pipelines."""
from __future__ import annotations

import numpy as _np

from ..io.io import DataIter, DataDesc
from .. import ndarray as nd

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """Returns batches from a ``gluon.data.DataLoader`` through the
    DataIter protocol (ref contrib/io.py:30). The last partial batch
    is zero-padded to batch_size with ``pad`` reporting the filler
    count, like the C-backed iterators."""

    def __init__(self, loader, data_name="data",
                 label_name="softmax_label", dtype="float32"):
        self._loader = loader
        self._iter = iter(loader)
        data, label = next(self._iter)
        batch_size = data.shape[0]
        super().__init__(batch_size)
        self.dtype = dtype
        self.provide_data = [DataDesc(data_name, tuple(data.shape),
                                      dtype)]
        self.provide_label = [DataDesc(label_name, tuple(label.shape),
                                       dtype)]
        self._current_batch = None
        self.reset()

    def reset(self):
        self._iter = iter(self._loader)

    def iter_next(self):
        try:
            self._current_batch = next(self._iter)
        except StopIteration:
            self._current_batch = None
        return self._current_batch is not None

    def _padded(self, arr):
        arr = arr.astype(self.dtype)
        pad = self.getpad()
        if pad:
            full = nd.zeros((self.batch_size,) + tuple(arr.shape[1:]),
                            dtype=self.dtype)
            full[:arr.shape[0]] = arr
            return [full]
        return [arr]

    def getdata(self):
        return self._padded(self._current_batch[0])

    def getlabel(self):
        return self._padded(self._current_batch[1])

    def getpad(self):
        return self.batch_size - self._current_batch[0].shape[0]

    def getindex(self):
        return None
