"""SVRG — stochastic variance-reduced gradient training (reference:
python/mxnet/contrib/svrg_optimization/{svrg_module,svrg_optimizer}.py).

The recipe: every ``update_freq`` epochs snapshot the parameters and
compute the FULL-dataset gradient at the snapshot; each step then uses
the corrected gradient  g_i(w) - g_i(w_snap) + g_full(w_snap), which
has the same expectation as g_i(w) but shrinking variance.
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """Module with SVRG-corrected updates (reference:
    svrg_module.py:29). Call :meth:`update_full_grads` once per
    ``update_freq`` epochs, then train normally."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, logger=logger,
                         context=context, **kwargs)
        if update_freq < 1:
            raise MXNetError("update_freq must be >= 1")
        self.update_freq = update_freq
        self._snap_params = None        # params at snapshot
        self._full_grads = None         # full grad at snapshot
        self._snap_mod = None

    def _ensure_snapshot_module(self):
        if self._snap_mod is None:
            self._snap_mod = Module(self._symbol,
                                    data_names=self.data_names,
                                    label_names=self.label_names,
                                    context=self._context)
            self._snap_mod.bind(self.data_shapes, self.label_shapes,
                                for_training=True, grad_req="add")
        return self._snap_mod

    def update_full_grads(self, train_data):
        """Snapshot current params and accumulate the full-dataset
        gradient at that snapshot (reference: svrg_module.py:214)."""
        assert self.binded and self.params_initialized
        args, auxs = self.get_params()
        self._snap_params = {k: v.copy() for k, v in args.items()}
        mod = self._ensure_snapshot_module()
        mod.init_params(arg_params=args, aux_params=auxs,
                        allow_missing=False, force_init=True)
        for g in mod._exec.grad_arrays:
            if g is not None:
                g[:] = 0
        train_data.reset()
        n_batches = 0
        for batch in train_data:
            mod.forward(batch, is_train=True)
            mod.backward()
            n_batches += 1
        train_data.reset()
        self._full_grads = {}
        for name, g in zip(mod._exec.arg_names,
                           mod._exec.grad_arrays):
            if g is not None:
                self._full_grads[name] = g / float(n_batches)

    def _svrg_correct(self, batch):
        """g(w) - g(w_snap) + g_full — leaves the corrected gradient in
        this module's grad arrays."""
        mod = self._ensure_snapshot_module()
        args, auxs = self.get_params()
        mod.init_params(arg_params=self._snap_params, aux_params=auxs,
                        allow_missing=False, force_init=True)
        for g in mod._exec.grad_arrays:
            if g is not None:
                g[:] = 0
        mod.forward(batch, is_train=True)
        mod.backward()
        snap_grads = dict(zip(mod._exec.arg_names,
                              mod._exec.grad_arrays))
        for name, g in zip(self._exec.arg_names,
                           self._exec.grad_arrays):
            if g is None:
                continue
            sg = snap_grads.get(name)
            fg = self._full_grads.get(name)
            if sg is not None and fg is not None:
                g[:] = g - sg + fg

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()
        if self._full_grads is not None:
            self._svrg_correct(data_batch)

    def fit(self, train_data, **kwargs):
        """Standard fit loop with a full-grad snapshot every
        ``update_freq`` epochs (reference: svrg_module.py:351)."""
        begin_epoch = kwargs.get("begin_epoch", 0)
        epoch_cb = kwargs.pop("epoch_end_callback", None)

        # snapshot before the very first epoch, then per update_freq
        def wrapped_epoch_cb(epoch, *cb_args):
            if (epoch + 1 - begin_epoch) % self.update_freq == 0:
                self.update_full_grads(train_data)
            if epoch_cb is not None:
                epoch_cb(epoch, *cb_args)

        self.bind(train_data.provide_data, train_data.provide_label,
                  for_training=True)
        if not self.params_initialized:
            from ..initializer import Uniform
            self.init_params(kwargs.get("initializer", Uniform(0.01)))
        self.update_full_grads(train_data)
        return super().fit(train_data,
                           epoch_end_callback=wrapped_epoch_cb,
                           **kwargs)
