"""Legacy contrib autograd API (parity:
python/mxnet/contrib/autograd.py — the pre-1.0 surface kept for old
scripts; thin shims over ``mxnet_tpu.autograd``)."""
from __future__ import annotations

import functools

from .. import autograd as _ag

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient",
           "grad_and_loss", "grad"]


def set_is_training(is_train):
    """Legacy global switch; returns the previous value."""
    prev = _ag.is_training()
    if is_train and not prev:
        _ag.set_training(True)
    elif not is_train and prev:
        _ag.set_training(False)
    return prev


def train_section():
    """``with train_section():`` — records AND runs in train mode."""
    return _ag.record(train_mode=True)


def test_section():
    """``with test_section():`` — records in predict mode."""
    return _ag.record(train_mode=False)


def mark_variables(variables, gradients, grad_reqs="write"):
    return _ag.mark_variables(variables, gradients, grad_reqs)


def backward(outputs, out_grads=None, retain_graph=False):
    return _ag.backward(outputs, head_grads=out_grads,
                        retain_graph=retain_graph)


def compute_gradient(outputs):
    backward(outputs)


def grad_and_loss(func, argnum=None):
    """Return a function computing both gradient of arguments and the
    loss value (ref contrib/autograd.py:163)."""
    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            argnums = [argnum] if isinstance(argnum, int) else argnum
            variables = [args[i] for i in argnums]
        grads = [x.zeros_like() for x in variables]
        mark_variables(variables, grads)
        with train_section():
            outputs = func(*args)
        backward([outputs] if not isinstance(outputs, (list, tuple))
                 else list(outputs))
        return grads, outputs
    return wrapped


def grad(func, argnum=None):
    """Gradient-only form of :func:`grad_and_loss`."""
    grad_with_loss_func = grad_and_loss(func, argnum)

    @functools.wraps(grad_with_loss_func)
    def wrapped(*args):
        return grad_with_loss_func(*args)[0]
    return wrapped
