"""INT8 model quantization flow (reference:
python/mxnet/contrib/quantization.py + the graph rewrite pass
src/operator/quantization/quantize_graph_pass.cc).

The flow mirrors the reference's three stages:
1. ``quantize_symbol`` — graph rewrite: eligible FullyConnected /
   Convolution nodes become quantize→quantized_op→requantize→dequantize
   chains (the pass's node substitution, done here on the Symbol IR).
2. ``_LayerOutputCollector``/calibration — run calibration batches and
   record per-tensor min/max (the 'naive' calib mode; entropy mode is
   out of scope and documented as such).
3. ``quantize_model`` — apply 1 with ranges from 2 baked into the
   requantize nodes, returning (qsym, qarg_params, aux_params).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from .. import symbol as sym_mod

__all__ = ["quantize_model", "quantize_symbol", "calib_graph",
           "calibrate_ranges"]

_QUANTIZABLE = {"FullyConnected", "Convolution"}


def _collect_layer_ranges(symbol, arg_params, aux_params, ctx,
                          calib_data, num_calib_batches, data_name):
    """Run calibration batches eagerly, recording min/max of every
    quantizable node's input and output (naive calibration). Label
    variables get the batch's labels when provided, else zeros — loss
    heads like SoftmaxOutput pass activations through unchanged, so
    the recorded ranges are label-independent."""
    from ..ndarray.ndarray import invoke_nd
    ranges = {}
    batches = 0
    for batch in calib_data:
        datas = batch.data if hasattr(batch, "data") else [batch]
        x = datas[0]
        labels = list(getattr(batch, "label", None) or [])
        env = {}
        label_cursor = [0]

        def _label_value():
            if label_cursor[0] < len(labels):
                val = labels[label_cursor[0]]
                label_cursor[0] += 1
                return val
            return nd.zeros((x.shape[0],))

        for node in symbol._topo_nodes():
            if node.is_variable():
                if node.name == data_name:
                    env[(id(node), 0)] = x
                elif node.name in arg_params:
                    env[(id(node), 0)] = arg_params[node.name]
                elif node.name in aux_params:
                    env[(id(node), 0)] = aux_params[node.name]
                else:
                    # label (or other unbound) variable
                    env[(id(node), 0)] = _label_value()
                continue
            ins = [env[(id(s), i)] for (s, i) in node.inputs]
            outs = invoke_nd(node.op, ins, dict(node.attrs))
            outs = outs if isinstance(outs, list) else [outs]
            for i, o in enumerate(outs):
                env[(id(node), i)] = o
            if node.op.name in _QUANTIZABLE:
                v = outs[0].asnumpy()
                lo, hi = float(v.min()), float(v.max())
                if node.name in ranges:
                    plo, phi = ranges[node.name]
                    lo, hi = min(lo, plo), max(hi, phi)
                ranges[node.name] = (lo, hi)
        batches += 1
        if num_calib_batches and batches >= num_calib_batches:
            break
    if hasattr(calib_data, "reset"):
        calib_data.reset()
    return ranges


def calibrate_ranges(symbol, arg_params, aux_params, calib_data,
                     num_calib_batches=None, data_name="data"):
    """Naive calibration as a standalone step: run ``calib_data``
    batches through ``symbol`` eagerly and return the per-node
    ``{name: (min, max)}`` ranges of every quantizable node's output —
    the dict :func:`quantize_symbol` bakes into requantize nodes and
    ``deploy.export_compiled(quantize=True)`` records in the format-3
    artifact meta."""
    return _collect_layer_ranges(symbol, arg_params, aux_params, None,
                                 calib_data, num_calib_batches,
                                 data_name)


def quantize_symbol(symbol, excluded_symbols=(), offline_params=(),
                    calib_ranges=None):
    """Rewrite a Symbol graph to its INT8 form (reference: the
    MXQuantizeSymbol pass). Eligible nodes are replaced by
    quantize_v2 → _contrib_quantized_* → requantize → dequantize."""
    from ..symbol.symbol import create, var

    calib_ranges = calib_ranges or {}
    memo = {}

    def convert(node):
        if id(node) in memo:
            return memo[id(node)]
        if node.is_variable():
            out = sym_mod.Symbol([(node, 0)])
            memo[id(node)] = out
            return out
        ins = [convert(s)[i] for (s, i) in node.inputs]
        name = node.name
        if node.op.name in _QUANTIZABLE and name not in excluded_symbols:
            out = _quantized_replacement(node, ins,
                                         calib_ranges.get(name))
        else:
            out = create(node.op, ins, dict(node.attrs), name=name)
        memo[id(node)] = out
        return out

    heads = []
    for (n, i) in symbol._outputs:
        heads.append(convert(n)[i])
    return sym_mod.Group(heads) if len(heads) > 1 else heads[0]


def _quantized_replacement(node, ins, crange):
    """One float node → int8 chain."""
    from ..symbol.symbol import create
    name = node.name
    qname = "_contrib_quantized_" + \
        ("fully_connected" if node.op.name == "FullyConnected"
         else "conv")
    no_bias = bool(node.attrs.get("no_bias", False))
    data, weight = ins[0], ins[1]
    bias = None if no_bias or len(ins) < 3 else ins[2]

    qd = create("_contrib_quantize_v2", [data], {},
                name=name + "_quantize_data")
    qw = create("_contrib_quantize_v2", [weight], {},
                name=name + "_quantize_weight")
    operands = [qd[0], qw[0]]
    attrs = dict(node.attrs, no_bias=bias is None)
    if bias is not None:
        qb = create("_contrib_quantize_v2", [bias], {},
                    name=name + "_quantize_bias")
        operands.append(qb[0])
    operands += [qd[1], qd[2], qw[1], qw[2]]
    if bias is not None:
        operands += [qb[1], qb[2]]
    qout = create(qname, operands, attrs, name=name + "_quantized")
    req_attrs = {}
    if crange is not None:
        req_attrs = {"min_calib_range": crange[0],
                     "max_calib_range": crange[1]}
    req = create("_contrib_requantize", [qout[0], qout[1], qout[2]],
                 req_attrs, name=name + "_requantize")
    deq = create("_contrib_dequantize", [req[0], req[1], req[2]], {},
                 name=name + "_dequantize")
    return deq


def calib_graph(qsym, arg_params, aux_params, collector, **kwargs):
    """API-parity shim: ranges are applied in quantize_model."""
    return qsym


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=(), calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   num_calib_batches=None, quantized_dtype="int8",
                   logger=None):
    """Quantize a trained model (reference: quantization.py:388
    quantize_model). Returns (qsym, arg_params, aux_params)."""
    if quantized_dtype != "int8":
        raise MXNetError(
            "TPU quantization supports int8 only, got %s"
            % quantized_dtype)
    ranges = None
    if calib_mode is not None and calib_mode != "none":
        if calib_mode != "naive":
            raise MXNetError(
                "calib_mode '%s' is not supported (use 'naive'; entropy "
                "calibration is a documented omission)" % calib_mode)
        if calib_data is None:
            raise MXNetError("calib_mode='naive' requires calib_data")
        if num_calib_batches is None and num_calib_examples is not None:
            bs = getattr(calib_data, "batch_size", 0) or 1
            num_calib_batches = max(1, -(-int(num_calib_examples) // bs))
        ranges = _collect_layer_ranges(
            sym, arg_params, aux_params, ctx, calib_data,
            num_calib_batches, data_names[0])
    qsym = quantize_symbol(sym, excluded_symbols=set(excluded_sym_names),
                           calib_ranges=ranges)
    return qsym, dict(arg_params), dict(aux_params)
