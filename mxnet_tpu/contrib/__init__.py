"""Contrib namespace (reference: python/mxnet/contrib/)."""
from . import quantization  # noqa: F401
from . import text          # noqa: F401
from . import svrg_optimization  # noqa: F401
from . import onnx          # noqa: F401
from . import io            # noqa: F401
from . import autograd      # noqa: F401
from . import tensorboard   # noqa: F401

# legacy alias kept from earlier rounds
onnx_export = onnx.export_model
