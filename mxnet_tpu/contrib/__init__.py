"""Contrib namespace (reference: python/mxnet/contrib/)."""
from . import quantization  # noqa: F401
from . import text          # noqa: F401
from . import svrg_optimization  # noqa: F401


def onnx_export(*args, **kwargs):
    """ONNX export requires the `onnx` package, which is not present in
    this image (environment contract: no pip installs). The deploy
    artifact path is `HybridBlock.export` / `Symbol.save` (symbol.json
    + .params), loadable by `SymbolBlock.imports` (reference's own
    language-agnostic deploy pair)."""
    raise ImportError(
        "onnx is not available in this environment; use "
        "HybridBlock.export()/SymbolBlock.imports() for deployment "
        "artifacts")
