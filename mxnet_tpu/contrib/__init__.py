"""Contrib namespace (reference: python/mxnet/contrib/)."""
from . import quantization  # noqa: F401
