"""ONNX -> Symbol import (parity:
python/mxnet/contrib/onnx/onnx2mx/import_onnx.py).

``ir_to_symbol`` consumes the same plain-dict graph IR that
``mx2onnx.symbol_to_onnx_ir`` emits — so export->import round-trips
are testable without the onnx package. ``import_model`` reads a real
.onnx file (gated on ``import onnx``) by first lowering the proto to
the IR dict, then reusing the same reconstruction.
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError

__all__ = ["ir_to_symbol", "import_model", "onnx_to_ir"]


def _p(attrs, key, default=None):
    return attrs.get(key, default)


def ir_to_symbol(ir):
    """Rebuild (sym, arg_params, aux_params) from the ONNX graph IR."""
    from ... import symbol as sym_mod
    from ...ndarray import array as nd_array

    values = {}                       # onnx tensor name -> Symbol
    inits = ir["initializers"]
    for name, _shape in ir["inputs"]:
        values[name] = sym_mod.var(name)
    param_syms = {}

    def sym_of(name):
        if name in values:
            return values[name]
        if name in inits:
            if name not in param_syms:
                param_syms[name] = sym_mod.var(name)
            return param_syms[name]
        raise MXNetError("ONNX import: undefined tensor %r" % name)

    arg_params = {}
    aux_params = {}
    for node in ir["nodes"]:
        op = node["op_type"]
        a = node["attrs"]
        ins = node["inputs"]
        out = node["outputs"][0]
        name = node["name"]
        if op == "Conv":
            ph, pw = a["pads"][0], a["pads"][1]
            res = sym_mod.create("Convolution",
                                 [sym_of(x) for x in ins],
                                 {"kernel": tuple(a["kernel_shape"]),
                                  "stride": tuple(a["strides"]),
                                  "dilate": tuple(a.get(
                                      "dilations", (1, 1))),
                                  "pad": (ph, pw),
                                  "num_group": int(a.get("group", 1)),
                                  "num_filter": int(
                                      inits[ins[1]].shape[0]),
                                  "no_bias": len(ins) < 3},
                                 name=name)
        elif op == "BatchNormalization":
            res = sym_mod.create("BatchNorm",
                                 [sym_of(x) for x in ins],
                                 {"eps": float(a.get("epsilon", 1e-5)),
                                  "momentum": float(a.get(
                                      "momentum", 0.9)),
                                  "fix_gamma": False},
                                 name=name)
            for aux_name in ins[3:5]:     # mean, var are aux state
                if aux_name in inits:
                    aux_params[aux_name] = nd_array(inits[aux_name])
        elif op in ("Relu", "Sigmoid", "Tanh", "Softplus", "Softsign"):
            act = {"Relu": "relu", "Sigmoid": "sigmoid",
                   "Tanh": "tanh", "Softplus": "softrelu",
                   "Softsign": "softsign"}[op]
            res = sym_mod.create("Activation", [sym_of(ins[0])],
                                 {"act_type": act}, name=name)
        elif op in ("MaxPool", "AveragePool"):
            ph, pw = a["pads"][0], a["pads"][1]
            res = sym_mod.create(
                "Pooling", [sym_of(ins[0])],
                {"kernel": tuple(a["kernel_shape"]),
                 "stride": tuple(a.get("strides", (1, 1))),
                 "pad": (ph, pw),
                 "pool_type": "max" if op == "MaxPool" else "avg"},
                name=name)
        elif op in ("GlobalMaxPool", "GlobalAveragePool"):
            res = sym_mod.create(
                "Pooling", [sym_of(ins[0])],
                {"kernel": (1, 1), "global_pool": True,
                 "pool_type": "max" if op == "GlobalMaxPool"
                 else "avg"}, name=name)
        elif op == "Flatten":
            res = sym_mod.create("Flatten", [sym_of(ins[0])], {},
                                 name=name)
        elif op == "Gemm":
            assert int(a.get("transB", 0)) == 1, \
                "ONNX import: only transB=1 Gemm supported"
            res = sym_mod.create(
                "FullyConnected", [sym_of(x) for x in ins],
                {"num_hidden": int(inits[ins[1]].shape[0]),
                 "no_bias": len(ins) < 3, "flatten": False},
                name=name)
        elif op == "Concat":
            res = sym_mod.create("Concat", [sym_of(x) for x in ins],
                                 {"dim": int(a.get("axis", 1)),
                                  "num_args": len(ins)}, name=name)
        elif op == "Dropout":
            res = sym_mod.create("Dropout", [sym_of(ins[0])],
                                 {"p": float(a.get("ratio", 0.5))},
                                 name=name)
        elif op == "Clip":
            res = sym_mod.create("clip", [sym_of(ins[0])],
                                 {"a_min": float(a.get("min", 0.0)),
                                  "a_max": float(a.get("max", 1.0))},
                                 name=name)
        elif op == "Softmax":
            res = sym_mod.create("softmax", [sym_of(ins[0])],
                                 {"axis": int(a.get("axis", -1))},
                                 name=name)
        elif op in ("Add", "Sub", "Mul", "Div"):
            mxop = {"Add": "broadcast_add", "Sub": "broadcast_sub",
                    "Mul": "broadcast_mul",
                    "Div": "broadcast_div"}[op]
            res = sym_mod.create(mxop, [sym_of(x) for x in ins], {},
                                 name=name)
        elif op == "Reshape":
            shape = tuple(int(s) for s in inits[ins[1]])
            res = sym_mod.create("Reshape", [sym_of(ins[0])],
                                 {"shape": shape}, name=name)
        elif op == "Transpose":
            res = sym_mod.create("transpose", [sym_of(ins[0])],
                                 {"axes": tuple(a.get("perm", ()))},
                                 name=name)
        elif op == "ReduceMean":
            res = sym_mod.create(
                "mean", [sym_of(ins[0])],
                {"axis": tuple(a.get("axes", ())) or None,
                 "keepdims": bool(a.get("keepdims", 0))}, name=name)
        elif op == "Pad":
            res = sym_mod.create(
                "Pad", [sym_of(ins[0])],
                {"mode": str(a.get("mode", "constant")),
                 "pad_width": tuple(
                     x for pair in zip(
                         a["pads"][:len(a["pads"]) // 2],
                         a["pads"][len(a["pads"]) // 2:])
                     for x in pair),
                 "constant_value": float(a.get("value", 0.0))},
                name=name)
        else:
            raise MXNetError(
                "ONNX import: unsupported op_type %r" % op)
        values[out] = res

    heads = [values[o] for o in ir["outputs"]]
    out_sym = heads[0] if len(heads) == 1 \
        else sym_mod.Group(heads)
    aux_names = set(out_sym.list_auxiliary_states())
    for pname, psym in param_syms.items():
        del psym
        if pname in aux_params:
            continue
        target = aux_params if pname in aux_names else arg_params
        target[pname] = nd_array(inits[pname])
    return out_sym, arg_params, aux_params


def onnx_to_ir(model):
    """Lower an onnx.ModelProto to the plain-dict graph IR."""
    from onnx import numpy_helper
    g = model.graph
    inits = {t.name: numpy_helper.to_array(t) for t in g.initializer}
    nodes = []
    for n in g.node:
        attrs = {}
        for att in n.attribute:
            import onnx as _onnx
            attrs[att.name] = _onnx.helper.get_attribute_value(att)
            if isinstance(attrs[att.name], bytes):
                attrs[att.name] = attrs[att.name].decode()
        nodes.append({"op_type": n.op_type, "inputs": list(n.input),
                      "outputs": list(n.output), "name": n.name,
                      "attrs": attrs})
    inputs = []
    for vi in g.input:
        if vi.name in inits:
            continue
        shape = tuple(d.dim_value
                      for d in vi.type.tensor_type.shape.dim)
        inputs.append((vi.name, shape))
    return {"nodes": nodes, "initializers": inits, "inputs": inputs,
            "outputs": [o.name for o in g.output]}


def import_model(model_file):
    """Read a .onnx file -> (sym, arg_params, aux_params). Requires the
    onnx package (the IR reconstruction itself does not)."""
    try:
        import onnx
    except ImportError:
        raise ImportError(
            "onnx is not available in this environment; use "
            "SymbolBlock.imports on a HybridBlock.export deploy pair "
            "instead")
    model = onnx.load(model_file)
    return ir_to_symbol(onnx_to_ir(model))
