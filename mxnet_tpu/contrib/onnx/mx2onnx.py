"""Symbol graph -> ONNX export (parity:
python/mxnet/contrib/onnx/mx2onnx/export_onnx.py + _op_translations.py).

Two layers:
1. ``symbol_to_onnx_ir`` — the real work: walk the Symbol JSON graph
   through a per-op converter registry into a plain-dict ONNX graph IR
   (node dicts with op_type/inputs/outputs/attrs + numpy initializers).
   Needs NO onnx package, so the converter logic is fully testable in
   this environment, and ``onnx2mx.ir_to_symbol`` can round-trip it.
2. ``ir_to_onnx`` / ``export_model`` — mechanical proto assembly via
   onnx.helper, gated on ``import onnx`` (ImportError carries the
   deploy-pair alternative).

Covered op subset = the Gluon model zoo: Convolution, BatchNorm,
Activation, Pooling, FullyConnected, Flatten, Concat, Dropout, clip,
softmax/SoftmaxOutput, elementwise/broadcast add-mul-sub-div, Reshape,
transpose, Pad, mean.
"""
from __future__ import annotations

import json

import numpy as _np

from ...base import MXNetError, atomic_write_bytes
from ...ops.registry import get_op, normalize_attrs

__all__ = ["symbol_to_onnx_ir", "ir_to_onnx", "export_model",
           "register_converter"]

MX2ONNX = {}


def register_converter(*op_names):
    def deco(fn):
        for n in op_names:
            MX2ONNX[n] = fn
        return fn
    return deco


def _node(op_type, inputs, outputs, name, **attrs):
    return {"op_type": op_type, "inputs": list(inputs),
            "outputs": list(outputs), "name": name, "attrs": attrs}


def _pair(v, default):
    if v is None or v == ():
        return (default, default)
    if isinstance(v, int):
        return (v, v)
    t = tuple(int(x) for x in v)
    return t if len(t) == 2 else (t[0], t[0])


class _Ctx:
    """Converter context: initializer dict (converters may add or
    rewrite entries, e.g. fix_gamma) and a unique-name counter."""

    def __init__(self, initializers):
        self.initializers = initializers
        self._n = 0

    def fresh(self, base):
        self._n += 1
        return "%s__%d" % (base, self._n)


# ---------------------------------------------------------------------------
# converters (mx node, input names, normalized attrs, out name, ctx)
# ---------------------------------------------------------------------------

@register_converter("Convolution")
def _conv(node, inputs, a, out, ctx):
    kh, kw = tuple(int(k) for k in a["kernel"])
    sh, sw = _pair(a.get("stride"), 1)
    dh, dw = _pair(a.get("dilate"), 1)
    ph, pw = _pair(a.get("pad"), 0)
    ins = inputs[:2] if a.get("no_bias") else inputs[:3]
    return [_node("Conv", ins, [out], node["name"],
                  kernel_shape=(kh, kw), strides=(sh, sw),
                  dilations=(dh, dw), pads=(ph, pw, ph, pw),
                  group=int(a.get("num_group", 1)))]


@register_converter("BatchNorm", "BatchNorm_v1")
def _bn(node, inputs, a, out, ctx):
    if a.get("fix_gamma", True):
        gname = inputs[1]
        if gname in ctx.initializers:
            ctx.initializers[gname] = _np.ones_like(
                ctx.initializers[gname])
    return [_node("BatchNormalization", inputs[:5], [out],
                  node["name"], epsilon=float(a.get("eps", 1e-3)),
                  momentum=float(a.get("momentum", 0.9)))]


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


@register_converter("Activation")
def _act(node, inputs, a, out, ctx):
    t = a.get("act_type", "relu")
    if t not in _ACT:
        raise MXNetError("ONNX export: unsupported act_type %r" % t)
    return [_node(_ACT[t], inputs[:1], [out], node["name"])]


@register_converter("Pooling")
def _pool(node, inputs, a, out, ctx):
    ptype = a.get("pool_type", "max")
    if ptype not in ("max", "avg"):
        raise MXNetError("ONNX export: unsupported pool_type %r"
                         % ptype)
    if a.get("global_pool", False):
        op = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        return [_node(op, inputs[:1], [out], node["name"])]
    kh, kw = _pair(a.get("kernel"), 1)
    sh, sw = _pair(a.get("stride"), 1)
    ph, pw = _pair(a.get("pad"), 0)
    op = "MaxPool" if ptype == "max" else "AveragePool"
    extra = {} if ptype == "max" else {
        "count_include_pad": 1
        if a.get("count_include_pad", True) else 0}
    return [_node(op, inputs[:1], [out], node["name"],
                  kernel_shape=(kh, kw), strides=(sh, sw),
                  pads=(ph, pw, ph, pw), **extra)]


@register_converter("FullyConnected")
def _fc(node, inputs, a, out, ctx):
    nodes = []
    data = inputs[0]
    if a.get("flatten", True):
        flat = ctx.fresh(node["name"] + "_flatten")
        nodes.append(_node("Flatten", [data], [flat],
                           flat, axis=1))
        data = flat
    ins = [data, inputs[1]]
    if not a.get("no_bias", False) and len(inputs) > 2:
        ins.append(inputs[2])
    nodes.append(_node("Gemm", ins, [out], node["name"],
                       alpha=1.0, beta=1.0, transA=0, transB=1))
    return nodes


@register_converter("Flatten")
def _flatten(node, inputs, a, out, ctx):
    return [_node("Flatten", inputs[:1], [out], node["name"], axis=1)]


@register_converter("Concat")
def _concat(node, inputs, a, out, ctx):
    return [_node("Concat", inputs, [out], node["name"],
                  axis=int(a.get("dim", 1)))]


@register_converter("Dropout")
def _dropout(node, inputs, a, out, ctx):
    return [_node("Dropout", inputs[:1], [out], node["name"],
                  ratio=float(a.get("p", 0.5)))]


@register_converter("clip")
def _clip(node, inputs, a, out, ctx):
    return [_node("Clip", inputs[:1], [out], node["name"],
                  min=float(a.get("a_min", 0.0)),
                  max=float(a.get("a_max", 1.0)))]


@register_converter("softmax")
def _softmax(node, inputs, a, out, ctx):
    return [_node("Softmax", inputs[:1], [out], node["name"],
                  axis=int(a.get("axis", -1)))]


@register_converter("SoftmaxOutput")
def _softmax_output(node, inputs, a, out, ctx):
    # deploy-time semantics: plain softmax over the class axis
    return [_node("Softmax", inputs[:1], [out], node["name"], axis=1)]


_BINOP = {"broadcast_add": "Add", "elemwise_add": "Add",
          "_plus": "Add", "_Plus": "Add",
          "broadcast_sub": "Sub", "elemwise_sub": "Sub",
          "broadcast_mul": "Mul", "elemwise_mul": "Mul",
          "broadcast_div": "Div", "elemwise_div": "Div"}


@register_converter(*_BINOP)
def _binop(node, inputs, a, out, ctx):
    return [_node(_BINOP[node["op"]], inputs[:2], [out],
                  node["name"])]


@register_converter("Reshape")
def _reshape(node, inputs, a, out, ctx):
    shape_name = ctx.fresh(node["name"] + "_shape")
    ctx.initializers[shape_name] = _np.asarray(
        tuple(a.get("shape", ())), _np.int64)
    return [_node("Reshape", [inputs[0], shape_name], [out],
                  node["name"])]


@register_converter("transpose")
def _transpose(node, inputs, a, out, ctx):
    return [_node("Transpose", inputs[:1], [out], node["name"],
                  perm=tuple(int(x) for x in a.get("axes", ())))]


@register_converter("Pad")
def _pad(node, inputs, a, out, ctx):
    pw = tuple(int(x) for x in a.get("pad_width", ()))
    n = len(pw) // 2
    begins = pw[0::2]
    ends = pw[1::2]
    return [_node("Pad", inputs[:1], [out], node["name"],
                  mode=str(a.get("mode", "constant")),
                  pads=tuple(begins) + tuple(ends),
                  value=float(a.get("constant_value", 0.0)))]


@register_converter("mean")
def _mean(node, inputs, a, out, ctx):
    ax = a.get("axis", None)
    attrs = {"keepdims": 1 if a.get("keepdims", False) else 0}
    if ax is not None and ax != ():
        axes = (ax,) if isinstance(ax, int) else tuple(ax)
        attrs["axes"] = tuple(int(x) for x in axes)
    return [_node("ReduceMean", inputs[:1], [out], node["name"],
                  **attrs)]


# ---------------------------------------------------------------------------
# graph walk
# ---------------------------------------------------------------------------

def symbol_to_onnx_ir(sym, params, input_shapes):
    """Walk ``sym``'s JSON graph into the ONNX IR dict.

    params: name -> numpy array (arg + aux merged).
    input_shapes: name -> shape for the data inputs.
    Returns {"nodes", "initializers", "inputs", "outputs"}.
    """
    graph = json.loads(sym.tojson())
    nodes = graph["nodes"]

    def out_name(nid, idx):
        base = nodes[nid]["name"]
        return base if idx == 0 else "%s_out%d" % (base, idx)

    initializers = {}
    inputs = []
    ctx = _Ctx(initializers)
    ir_nodes = []
    for nid, node in enumerate(nodes):
        if node["op"] == "null":
            name = node["name"]
            if name in params:
                initializers[name] = _np.asarray(params[name])
            else:
                if name not in input_shapes:
                    raise MXNetError(
                        "ONNX export: no value or shape for input %r"
                        % name)
                inputs.append((name, tuple(input_shapes[name])))
            continue
        conv = MX2ONNX.get(node["op"])
        if conv is None:
            raise MXNetError(
                "ONNX export: no converter registered for op %r "
                "(supported: %s)" % (node["op"], sorted(MX2ONNX)))
        in_names = [out_name(i[0], i[1]) for i in node["inputs"]]
        attrs = normalize_attrs(get_op(node["op"]),
                                dict(node.get("attrs", {})))
        ir_nodes.extend(conv(node, in_names, attrs,
                             out_name(nid, 0), ctx))
    outputs = [out_name(h[0], h[1]) for h in graph["heads"]]
    return {"nodes": ir_nodes, "initializers": initializers,
            "inputs": inputs, "outputs": outputs}


def ir_to_onnx(ir, model_name="mxnet_tpu_model"):
    """Assemble an onnx.ModelProto from the IR. Requires the onnx
    package (gated; everything above this line runs without it)."""
    try:
        import onnx
        from onnx import helper, numpy_helper, TensorProto
    except ImportError:
        raise ImportError(
            "onnx is not available in this environment; "
            "symbol_to_onnx_ir still produced the full graph IR — "
            "install onnx to emit the .onnx file, or use "
            "HybridBlock.export()/SymbolBlock.imports() deploy pairs")
    nodes = [helper.make_node(n["op_type"], n["inputs"], n["outputs"],
                              name=n["name"], **n["attrs"])
             for n in ir["nodes"]]
    inits = [numpy_helper.from_array(v, name=k)
             for k, v in ir["initializers"].items()]
    inputs = [helper.make_tensor_value_info(
        n, TensorProto.FLOAT, list(s)) for n, s in ir["inputs"]]
    outputs = [helper.make_tensor_value_info(
        n, TensorProto.FLOAT, None) for n in ir["outputs"]]
    graph = helper.make_graph(nodes, model_name, inputs, outputs,
                              initializer=inits)
    model = helper.make_model(graph)
    onnx.checker.check_model(model)
    return model


def export_model(sym, params, input_shapes, onnx_file_path,
                 verbose=False):
    """The reference's export_model surface
    (mx2onnx/export_onnx.py): symbol + params + input shapes ->
    serialized .onnx file. Accepts a dict name->shape or a list of
    shapes matching the symbol's data inputs in order."""
    if not isinstance(input_shapes, dict):
        data_names = [n for n in sym.list_arguments()
                      if n not in params]
        input_shapes = dict(zip(data_names, input_shapes))
    np_params = {k: (v.asnumpy() if hasattr(v, "asnumpy")
                     else _np.asarray(v))
                 for k, v in params.items()}
    ir = symbol_to_onnx_ir(sym, np_params, input_shapes)
    model = ir_to_onnx(ir)
    # the shared durable-write discipline: never leave a truncated
    # .onnx on a preempted export
    atomic_write_bytes(onnx_file_path, model.SerializeToString())
    if verbose:
        print("exported", onnx_file_path)
    return onnx_file_path
