"""ONNX interop (parity: python/mxnet/contrib/onnx/).

The converter layer (Symbol JSON <-> plain-dict graph IR) runs without
the onnx package; only reading/writing actual .onnx protos is gated on
``import onnx``.
"""
from .mx2onnx import (symbol_to_onnx_ir, ir_to_onnx, export_model,
                      register_converter)
from .onnx2mx import ir_to_symbol, onnx_to_ir, import_model

__all__ = ["symbol_to_onnx_ir", "ir_to_onnx", "export_model",
           "register_converter", "ir_to_symbol", "onnx_to_ir",
           "import_model"]
