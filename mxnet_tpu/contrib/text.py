"""Text utilities: vocabulary + token embeddings (reference:
python/mxnet/contrib/text/{vocab,embedding,utils}.py).

Own design: the vocabulary is an immutable index built once from a
counter; embeddings are one dense (V, D) NDArray assembled at load,
so lookups are plain `take` gathers on device.
"""
from __future__ import annotations

import collections
import re

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd

__all__ = ["count_tokens_from_str", "Vocabulary", "TokenEmbedding",
           "CustomEmbedding"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Token counter over a delimited string (reference:
    contrib/text/utils.py:31)."""
    source = source_str.lower() if to_lower else source_str
    tokens = [t for t in re.split(
        "[%s%s]" % (re.escape(token_delim), re.escape(seq_delim)),
        source) if t]
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(tokens)
    return counter


class Vocabulary:
    """Token ↔ index mapping ordered by frequency (reference:
    contrib/text/vocab.py:33). Index 0 is the unknown token; reserved
    tokens follow, then counted tokens by (count desc, token asc)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if len(set(reserved_tokens)) != len(reserved_tokens) or \
                unknown_token in reserved_tokens:
            raise MXNetError(
                "reserved tokens must be unique and exclude the "
                "unknown token")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens
        self._idx_to_token = [unknown_token] + reserved_tokens
        if counter is not None:
            ordered = sorted(counter.items(),
                             key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                ordered = ordered[:most_freq_count]
            for token, freq in ordered:
                if freq < min_freq or token == unknown_token \
                        or token in reserved_tokens:
                    continue
                self._idx_to_token.append(token)
        self._token_to_idx = {t: i
                              for i, t in enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self):
                raise MXNetError("token index %d out of range" % i)
        out = [self._idx_to_token[i] for i in idxs]
        return out[0] if single else out


class TokenEmbedding(Vocabulary):
    """Pretrained token embeddings over a vocabulary (reference:
    contrib/text/embedding.py:141). The table is ONE (V, D) NDArray;
    unknown tokens get ``init_unknown_vec`` rows."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def _load_embedding_file(self, file_path, elem_delim=" ",
                             encoding="utf8"):
        table = {}
        dim = None
        with open(file_path, encoding=encoding) as f:
            for lineno, line in enumerate(f):
                cells = line.rstrip().split(elem_delim)
                if len(cells) < 2:
                    continue
                if lineno == 0 and len(cells) == 2 and \
                        all(c.isdigit() for c in cells):
                    continue            # word2vec "vocab dim" header
                vec = [float(x) for x in cells[1:] if x]
                if dim is None:
                    dim = len(vec)
                if len(vec) != dim:
                    continue            # malformed row
                table[cells[0]] = vec
        if dim is None:
            raise MXNetError("no vectors found in %s" % file_path)
        return table, dim

    def _build_table(self, loaded, dim, init_unknown_vec):
        self._vec_len = dim
        mat = np.array(init_unknown_vec(shape=(len(self), dim))
                       .asnumpy())
        for i, token in enumerate(self._idx_to_token):
            if token in loaded:
                mat[i] = loaded[token]
        self._idx_to_vec = nd.array(mat)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idxs = []
        for t in toks:
            i = self._token_to_idx.get(t, 0)
            if i == 0 and lower_case_backup:
                i = self._token_to_idx.get(t.lower(), 0)
            idxs.append(i)
        vecs = self._idx_to_vec.take(nd.array(idxs, dtype="int32"))
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        toks = [tokens] if isinstance(tokens, str) else tokens
        idxs = [self._token_to_idx[t] for t in toks]
        data = np.array(self._idx_to_vec.asnumpy())
        data[np.asarray(idxs)] = new_vectors.asnumpy().reshape(
            len(idxs), -1)
        self._idx_to_vec = nd.array(data)


class CustomEmbedding(TokenEmbedding):
    """Embeddings loaded from a user token-vector file (reference:
    contrib/text/embedding.py CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", init_unknown_vec=nd.zeros,
                 vocabulary=None, **kwargs):
        loaded, dim = self._load_embedding_file(
            pretrained_file_path, elem_delim, encoding)
        if vocabulary is not None:
            self.__dict__.update(vocabulary.__dict__)
        else:
            counter = collections.Counter(loaded.keys())
            super().__init__(counter=counter, **kwargs)
        self._build_table(loaded, dim, init_unknown_vec)
