"""TensorBoard metric logging callback (parity:
python/mxnet/contrib/tensorboard.py). The writer backend is optional:
mxboard / tensorboardX / torch.utils.tensorboard are tried in order;
without any, construction raises ImportError with guidance."""
from __future__ import annotations

__all__ = ["LogMetricsCallback"]


def _find_writer(logging_dir):
    try:
        from mxboard import SummaryWriter          # noqa: F401
        return SummaryWriter(logging_dir)
    except ImportError:
        pass
    try:
        from tensorboardX import SummaryWriter    # noqa: F401
        return SummaryWriter(logging_dir)
    except ImportError:
        pass
    try:
        from torch.utils.tensorboard import SummaryWriter
        return SummaryWriter(logging_dir)
    except ImportError:
        raise ImportError(
            "LogMetricsCallback needs a SummaryWriter backend: install "
            "mxboard, tensorboardX, or torch")


class LogMetricsCallback:
    """Epoch/batch-end callback writing metric scalars to TensorBoard
    event files (ref contrib/tensorboard.py:45).

    ``log_telemetry=True`` additionally exports the active telemetry
    run's step-time p50, samples/sec, and goodput (the same numbers
    ``telemetry.report()`` returns) as ``telemetry/*`` scalars."""

    def __init__(self, logging_dir, prefix=None, log_telemetry=False):
        self.prefix = prefix
        self.log_telemetry = log_telemetry
        self.summary_writer = _find_writer(logging_dir)
        self._step = 0

    def __call__(self, param):
        if param.eval_metric is None and not self.log_telemetry:
            return
        step = getattr(param, "epoch", None)
        if step is None:
            step = self._step
        self._step += 1
        if param.eval_metric is not None:
            for name, value in param.eval_metric.get_name_value():
                if self.prefix is not None:
                    name = "%s-%s" % (self.prefix, name)
                self.summary_writer.add_scalar(name, value,
                                               global_step=step)
        if self.log_telemetry:
            self._write_telemetry(step)

    def _write_telemetry(self, step):
        # quick_stats, not report(): this runs per batch-end and must
        # not pay for comms/memory copies it doesn't chart
        from .. import telemetry
        stats = telemetry.quick_stats() if telemetry.enabled() else None
        if not stats or not stats.get("steps"):
            return
        for key in ("samples_per_sec", "goodput", "step_time_ms_p50"):
            if stats.get(key) is not None:
                self.summary_writer.add_scalar(
                    "telemetry/" + key, stats[key], global_step=step)
