"""TensorBoard metric logging callback (parity:
python/mxnet/contrib/tensorboard.py). The writer backend is optional:
mxboard / tensorboardX / torch.utils.tensorboard are tried in order;
without any, construction raises ImportError with guidance."""
from __future__ import annotations

__all__ = ["LogMetricsCallback"]


def _find_writer(logging_dir):
    try:
        from mxboard import SummaryWriter          # noqa: F401
        return SummaryWriter(logging_dir)
    except ImportError:
        pass
    try:
        from tensorboardX import SummaryWriter    # noqa: F401
        return SummaryWriter(logging_dir)
    except ImportError:
        pass
    try:
        from torch.utils.tensorboard import SummaryWriter
        return SummaryWriter(logging_dir)
    except ImportError:
        raise ImportError(
            "LogMetricsCallback needs a SummaryWriter backend: install "
            "mxboard, tensorboardX, or torch")


class LogMetricsCallback:
    """Epoch/batch-end callback writing metric scalars to TensorBoard
    event files (ref contrib/tensorboard.py:45)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.summary_writer = _find_writer(logging_dir)
        self._step = 0

    def __call__(self, param):
        if param.eval_metric is None:
            return
        step = getattr(param, "epoch", None)
        if step is None:
            step = self._step
        self._step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value,
                                           global_step=step)
