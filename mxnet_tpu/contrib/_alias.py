"""Shared installer for the stripped `_contrib_*` op namespaces
(mx.nd.contrib.box_nms ≙ _contrib_box_nms), matching the reference's
generated contrib namespaces."""
from __future__ import annotations


def install_contrib_ops(namespace, make_stub):
    from .. import ops as _ops
    for name in _ops.list_ops():
        if name.startswith("_contrib_"):
            short = name[len("_contrib_"):]
            namespace.setdefault(short, make_stub(_ops.get_op(name)))
