"""Live operational metrics: a stdlib-only Prometheus ``/metrics``
HTTP endpoint plus an SLO watchdog — the scrape-and-alert half of the
observability stack.

The telemetry layer already aggregates everything an operator needs
(step-time percentiles, goodput, MFU, comm bytes, compile counts,
serving queue depth / occupancy / shed / timeout / latency) — but only
into a JSONL sink read *after* the run. This module serves the same
numbers live:

- **/metrics endpoint** — :func:`serve` starts a daemon-thread HTTP
  server (``http.server``, nothing beyond the stdlib) answering
  ``GET /metrics`` with Prometheus text exposition (format 0.0.4)
  rendered on demand from ``telemetry.report()``, the process-global
  ``profiler.counters()``, and every live
  :class:`~mxnet_tpu.serving.InferenceServer` (servers register
  themselves by weakref — a stopped/collected server drops out of the
  scrape). Binds ``127.0.0.1`` by default — metrics can leak workload
  shape, so exposing them beyond the host is an explicit
  ``MXNET_METRICS_HOST`` opt-in. ``MXNET_METRICS_PORT`` (picked up at
  ``telemetry.start`` and server construction) starts it from the
  environment; port 0 asks the OS for an ephemeral port (tests).

- **SLO watchdog** — :class:`Watchdog` observes the step records and
  cumulative serving snapshots already flowing through telemetry (the
  ``_watch_step``/``_watch_serving`` hooks, one ``None`` check each
  when off) and raises structured ``alert`` telemetry records plus a
  one-time warning per alert kind on: sustained step-time p50 drift
  against a rolling baseline (the baseline stops absorbing samples
  while a breach is building, so a regression cannot normalize
  itself), serving shed-rate breach, queue depth pinned at the bound,
  and per-replica service-time skew — the straggler primitive
  multi-host scale-out will lean on. Alerts render as the diagnose
  ``Alerts`` table and count into ``watchdog_alerts`` in
  ``profiler.counters()``.

Both pieces are off by default and cost nothing when off: the
watchdog hooks are ``None`` checks, and without :func:`serve` no
thread, socket, or render ever exists — a run with both off keeps a
byte-identical telemetry sink.
"""
from __future__ import annotations

import bisect
import itertools
import threading
import warnings
import weakref
from collections import deque

from . import envs

__all__ = ["serve", "stop_server", "server_port", "render",
           "register_server", "deregister_server",
           "register_decode_server", "deregister_decode_server",
           "register_router", "deregister_router",
           "Watchdog",
           "enable_watchdog",
           "disable_watchdog", "watchdog_enabled", "maybe_start",
           "LATENCY_BUCKETS_MS"]

# histogram bucket upper bounds (ms) for the recent-window serving
# latency histogram — roughly log-spaced over sub-ms..seconds
LATENCY_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0)

_servers = weakref.WeakSet()      # live InferenceServers
_decode_servers = weakref.WeakSet()   # live DecodeServers
_routers = weakref.WeakSet()      # live serving Routers
_http = None                      # (HTTPServer, thread)
_http_lock = threading.Lock()
_watchdog = None


_label_seq = itertools.count(2)
_register_lock = threading.Lock()


def deregister_server(server):
    """Drop a server from the scrape (called by
    ``InferenceServer.stop``; garbage collection also drops it). Its
    label becomes reusable by a replacement server."""
    with _register_lock:
        _servers.discard(server)


def _assign_label_locked(server, pool):
    label = getattr(server, "name", None) or "default"
    taken = {getattr(s, "_metrics_label", None) for s in pool}
    if label in taken:
        label = "%s-%d" % (label, next(_label_seq))
    server._metrics_label = label


def register_server(server):
    """Track one live InferenceServer for the scrape (weakref — a
    collected server drops out). Called from the server constructor.
    Each server gets a UNIQUE ``server=`` label: a second unnamed (or
    same-named) server is suffixed ``-2``, ``-3``, ... — duplicate
    label sets would make Prometheus reject the whole scrape. The
    check-and-assign runs under a lock so concurrently constructed
    servers cannot both claim one label."""
    with _register_lock:
        _assign_label_locked(server, _servers)
        _servers.add(server)


def register_decode_server(server):
    """Track one live ``serving.DecodeServer`` for the scrape — its
    own registry and ``mxnet_decode_*`` metric families (label
    uniqueness enforced within the decode set, same rules as
    :func:`register_server`)."""
    with _register_lock:
        _assign_label_locked(server, _decode_servers)
        _decode_servers.add(server)


def deregister_decode_server(server):
    """Drop a decode server from the scrape (called by
    ``DecodeServer.stop``)."""
    with _register_lock:
        _decode_servers.discard(server)


def register_router(router):
    """Track one live ``serving.Router`` for the scrape — the
    ``mxnet_router_*`` families (label uniqueness enforced within the
    router set, same rules as :func:`register_server`)."""
    with _register_lock:
        _assign_label_locked(router, _routers)
        _routers.add(router)


def deregister_router(router):
    """Drop a router from the scrape (called by ``Router.stop``)."""
    with _register_lock:
        _routers.discard(router)


def maybe_start(fresh_run=False):
    """Environment entry point (called from ``telemetry.start`` with
    ``fresh_run=True`` and from ``InferenceServer.__init__``): start
    the endpoint when ``MXNET_METRICS_PORT`` is set, the watchdog
    when ``MXNET_WATCHDOG=1``. A fresh telemetry run re-arms a FRESH
    watchdog — the previous run's rolling step-time baseline belongs
    to a different workload and would fire spurious drift alerts on
    the new one."""
    port = envs.get_int("MXNET_METRICS_PORT", None)
    if port is not None and _http is None:
        try:
            serve(int(port))
        except (OSError, ValueError) as exc:
            warnings.warn("livemetrics: cannot start /metrics on port "
                          "%s (%s) — endpoint disabled" % (port, exc))
    if envs.get_bool("MXNET_WATCHDOG") \
            and (_watchdog is None or fresh_run):
        enable_watchdog()


# ---------------------------------------------------------------------------
# Prometheus text rendering
# ---------------------------------------------------------------------------

def _esc(value):
    """Prometheus label-value escape."""
    return str(value).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


class _Page:
    """Accumulates one exposition page; emits # HELP/# TYPE once per
    metric family."""

    def __init__(self):
        self.lines = []
        self._seen = set()

    def add(self, name, value, labels=None, kind="gauge", help_=""):
        if value is None:
            return
        if name not in self._seen:
            self._seen.add(name)
            if help_:
                self.lines.append("# HELP %s %s" % (name, help_))
            self.lines.append("# TYPE %s %s" % (name, kind))
        if labels:
            lab = ",".join('%s="%s"' % (k, _esc(v))
                           for k, v in sorted(labels.items()))
            self.lines.append("%s{%s} %s" % (name, lab, _fmt(value)))
        else:
            self.lines.append("%s %s" % (name, _fmt(value)))

    def histogram(self, name, le_counts, sum_value, count,
                  labels=None, help_=""):
        """One histogram family per the exposition contract: TYPE is
        declared ONCE on the base name; the ``_bucket``/``_sum``/
        ``_count`` samples carry no TYPE lines of their own."""
        if name not in self._seen:
            self._seen.add(name)
            if help_:
                self.lines.append("# HELP %s %s" % (name, help_))
            self.lines.append("# TYPE %s histogram" % name)

        def line(suffix, value, extra=None):
            lab = dict(labels or {})
            if extra:
                lab.update(extra)
            if lab:
                body = ",".join('%s="%s"' % (k, _esc(v))
                                for k, v in sorted(lab.items()))
                self.lines.append("%s%s{%s} %s"
                                  % (name, suffix, body, _fmt(value)))
            else:
                self.lines.append("%s%s %s" % (name, suffix,
                                               _fmt(value)))

        for le, c in le_counts:
            line("_bucket", c, {"le": le})
        line("_bucket", count, {"le": "+Inf"})
        line("_sum", sum_value)
        line("_count", count)

    def text(self):
        return "\n".join(self.lines) + "\n"


def _fmt(v):
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float):
        return repr(v)
    return str(v)


def _render_training(page):
    """Training-run families from ``telemetry.report()`` — the same
    aggregates the JSONL summary carries, live."""
    from . import telemetry
    rep = telemetry.report()
    page.add("mxnet_telemetry_run_active",
             1 if telemetry.enabled() else 0,
             help_="1 while a telemetry run is active")
    if rep is None:
        return
    page.add("mxnet_steps_total", rep["steps"], kind="counter",
             help_="training steps recorded by the telemetry run")
    page.add("mxnet_samples_total", rep["samples"], kind="counter")
    page.add("mxnet_skipped_steps_total", rep["skipped_steps"],
             kind="counter",
             help_="steps skipped by the non-finite fault guard")
    page.add("mxnet_goodput_ratio", rep.get("goodput"))
    page.add("mxnet_samples_per_sec", rep.get("samples_per_sec"))
    st = rep.get("step_time_ms") or {}
    for q in ("p50", "p90", "p99"):
        page.add("mxnet_step_time_ms", st.get(q),
                 labels={"quantile": q},
                 help_="step wall time over the telemetry ring")
    for phase, ms in (rep.get("phases_ms") or {}).items():
        page.add("mxnet_phase_ms_total", ms, labels={"phase": phase},
                 kind="counter",
                 help_="accounted wall time per step phase")
    comm_kinds = {}
    for key, c in (rep.get("comms") or {}).items():
        kind = key.split(":", 1)[0]
        agg = comm_kinds.setdefault(kind, [0, 0])
        agg[0] += c.get("bytes", 0)
        agg[1] += c.get("calls", 0)
    for kind, (nbytes, calls) in sorted(comm_kinds.items()):
        page.add("mxnet_comm_bytes_total", nbytes,
                 labels={"kind": kind}, kind="counter",
                 help_="communication payload bytes per kind")
        page.add("mxnet_comm_calls_total", calls,
                 labels={"kind": kind}, kind="counter")
    cb = rep.get("compile") or {}
    page.add("mxnet_compiles_total", cb.get("count"), kind="counter",
             help_="XLA compiles this run (compile watch)")
    page.add("mxnet_compile_seconds_total", cb.get("total_s"),
             kind="counter")
    ub = rep.get("utilization") or {}
    mfu = ub.get("mfu") or {}
    for q in ("p50", "p90"):
        page.add("mxnet_mfu_ratio", mfu.get(q),
                 labels={"quantile": q},
                 help_="model-flops utilization vs device peak")
    # alert counts come from the watchdog's own monotonic per-kind
    # tallies, NOT the run summary's bounded alert window — a window
    # that trims old entries would make this "counter" decrease
    # mid-run, which rate()/increase() read as a bogus reset
    wd = _watchdog
    if wd is not None:
        for kind, n in sorted(wd.alerts().items()):
            page.add("mxnet_watchdog_alerts_total", n,
                     labels={"kind": kind}, kind="counter",
                     help_="SLO watchdog alerts by kind")


def _render_counters(page):
    from . import profiler
    for name, value in sorted(profiler.counters().items()):
        page.add("mxnet_profiler_counter", value,
                 labels={"name": name}, kind="counter",
                 help_="process-global profiler counters (fused step "
                       "cache, serving shed/timeout/dispatch, h2d, ...)")


def _render_serving(page):
    for srv in list(_servers):
        try:
            st = srv.stats()
            lats = srv.latency_snapshot()
        except Exception:
            continue                       # mid-shutdown server
        lab = {"server": getattr(srv, "_metrics_label", None)
               or "default"}
        page.add("mxnet_serving_requests_total", st["requests"],
                 labels=lab, kind="counter",
                 help_="requests submitted (admission attempts)")
        page.add("mxnet_serving_completed_total", st["completed"],
                 labels=lab, kind="counter")
        page.add("mxnet_serving_shed_total", st["shed"], labels=lab,
                 kind="counter",
                 help_="requests shed at the bounded admission queue")
        page.add("mxnet_serving_timeouts_total", st["timeouts"],
                 labels=lab, kind="counter")
        page.add("mxnet_serving_errors_total", st["errors"],
                 labels=lab, kind="counter")
        page.add("mxnet_serving_batches_total", st["batches"],
                 labels=lab, kind="counter")
        page.add("mxnet_serving_queue_depth", st["queue_depth"],
                 labels=lab,
                 help_="admission queue depth now (bound: max_queue)")
        page.add("mxnet_serving_queue_peak", st["queue_peak"],
                 labels=lab)
        page.add("mxnet_serving_queue_bound", st["max_queue"],
                 labels=lab)
        page.add("mxnet_serving_occupancy_ratio", st.get("occupancy"),
                 labels=lab,
                 help_="mean filled share of dispatched bucket slots")
        page.add("mxnet_serving_rps", st.get("rps"), labels=lab)
        lat = st.get("latency_ms") or {}
        for q in ("p50", "p90", "p99"):
            page.add("mxnet_serving_latency_ms", lat.get(q),
                     labels=dict(lab, quantile=q),
                     help_="request latency over the recent ring")
        for i, n in enumerate(st.get("replica_batches") or []):
            page.add("mxnet_serving_replica_batches_total", n,
                     labels=dict(lab, replica=str(i)), kind="counter")
        for i, ms in enumerate(st.get("replica_service_ms") or []):
            page.add("mxnet_serving_replica_service_ms", ms,
                     labels=dict(lab, replica=str(i)),
                     help_="mean batch service time per replica "
                           "(straggler signal)")
        # recent-window latency histogram (the ring, not all-time):
        # cumulative le buckets per the Prometheus histogram
        # contract, binned in one pass over the ring
        ms_vals = [v * 1e3 for v in lats]
        bins = [0] * (len(LATENCY_BUCKETS_MS) + 1)
        for v in ms_vals:
            bins[bisect.bisect_left(LATENCY_BUCKETS_MS, v)] += 1
        le_counts, cum = [], 0
        for le, c in zip(LATENCY_BUCKETS_MS, bins):
            cum += c
            le_counts.append(("%g" % le, cum))
        page.histogram(
            "mxnet_serving_latency_recent_ms", le_counts,
            round(sum(ms_vals), 3), len(ms_vals), labels=lab,
            help_="request latency histogram over the recent "
                  "latency ring")


def _render_decode(page):
    for srv in list(_decode_servers):
        try:
            st = srv.stats()
        except Exception:
            continue                       # mid-shutdown server
        lab = {"server": getattr(srv, "_metrics_label", None)
               or "default"}
        for key, help_ in (("requests", "generations submitted"),
                           ("completed", ""), ("cancelled", ""),
                           ("timeouts", ""), ("shed", ""),
                           ("preempted", "evicted under KV-pool "
                                         "pressure"),
                           ("errors", ""),
                           ("prefill_steps", ""),
                           ("decode_steps", ""),
                           ("tokens_out", "tokens generated")):
            page.add("mxnet_decode_%s_total" % key, st.get(key),
                     labels=lab, kind="counter", help_=help_)
        page.add("mxnet_decode_queue_depth", st.get("queue_depth"),
                 labels=lab)
        page.add("mxnet_decode_active", st.get("active"), labels=lab,
                 help_="requests holding decode slots now")
        page.add("mxnet_decode_window", st.get("window"), labels=lab,
                 help_="decode-step batch width (MXNET_DECODE_WINDOW)")
        page.add("mxnet_decode_tokens_per_sec",
                 st.get("tokens_per_sec"), labels=lab)
        page.add("mxnet_decode_prefill_fraction",
                 st.get("prefill_fraction"), labels=lab,
                 help_="prefill share of scheduler steps (the "
                       "continuous-batching mix)")
        for q in ("p50", "p99"):
            page.add("mxnet_decode_inter_token_ms",
                     (st.get("inter_token_ms") or {}).get(q),
                     labels=dict(lab, quantile=q),
                     help_="inter-token latency over the recent ring")
            page.add("mxnet_decode_ttft_ms",
                     (st.get("ttft_ms") or {}).get(q),
                     labels=dict(lab, quantile=q),
                     help_="time to first token (submit -> prefill "
                           "emit)")
        kv = st.get("kv") or {}
        page.add("mxnet_decode_kv_pages", kv.get("pages"), labels=lab,
                 help_="usable pages of the paged KV pool")
        page.add("mxnet_decode_kv_pages_used", kv.get("used"),
                 labels=lab)
        page.add("mxnet_decode_kv_pages_peak", kv.get("peak_used"),
                 labels=lab)
        page.add("mxnet_decode_kv_evicted_total", kv.get("evicted"),
                 labels=lab, kind="counter",
                 help_="pages reclaimed (the kv_evict path)")
        page.add("mxnet_decode_weight_swaps_total", st.get("swaps"),
                 labels=lab, kind="counter")
        page.add("mxnet_decode_weight_version",
                 st.get("weight_version"), labels=lab,
                 help_="parameter generation serving new requests")
        px = st.get("prefix") or {}
        if px.get("enabled"):
            page.add("mxnet_prefix_hits_total", px.get("hits"),
                     labels=lab, kind="counter",
                     help_="prompts admitted onto shared prefix pages")
            page.add("mxnet_prefix_misses_total", px.get("misses"),
                     labels=lab, kind="counter")
            page.add("mxnet_prefix_hit_rate", px.get("hit_rate"),
                     labels=lab)
            page.add("mxnet_prefix_hit_tokens_total",
                     px.get("hit_tokens"), labels=lab, kind="counter",
                     help_="prompt tokens served from the index "
                           "instead of prefill")
            page.add("mxnet_prefix_bytes_saved_total",
                     px.get("bytes_saved"), labels=lab,
                     kind="counter",
                     help_="K/V bytes not recomputed thanks to "
                           "sharing")
            page.add("mxnet_prefix_cow_splits_total",
                     px.get("cow_splits"), labels=lab, kind="counter",
                     help_="copy-on-write page splits")
            page.add("mxnet_prefix_cow_degraded_total",
                     px.get("cow_degraded"), labels=lab,
                     kind="counter",
                     help_="kv_cow faults degraded to private "
                           "re-prefill")
            pool = px.get("pool") or {}
            page.add("mxnet_prefix_entries", pool.get("entries"),
                     labels=lab, help_="pages held by the index")
            page.add("mxnet_prefix_shared_pages",
                     pool.get("shared_pages"), labels=lab,
                     help_="pages with more than one holder now")
            page.add("mxnet_prefix_evicted_total", pool.get("evicted"),
                     labels=lab, kind="counter",
                     help_="cold index entries reclaimed under "
                           "pressure")
        for owner, o in sorted((kv.get("owners") or {}).items()):
            olab = dict(lab, model=owner)
            page.add("mxnet_prefix_pool_pages_used", o.get("used"),
                     labels=olab,
                     help_="shared-pool pages held per model")
            if o.get("quota"):
                page.add("mxnet_prefix_pool_quota", o.get("quota"),
                         labels=olab)


def _render_router(page):
    for router in list(_routers):
        try:
            st = router.stats()
        except Exception:
            continue                       # mid-shutdown router
        lab = {"router": getattr(router, "_metrics_label", None)
               or "default"}
        for key, help_ in (("requests", "sessions admitted"),
                           ("dispatched", ""), ("completed", ""),
                           ("failed", ""), ("cancelled", ""),
                           ("shed", ""), ("timeouts", ""),
                           ("throttles", "dispatch rounds a tenant "
                                         "sat out its token bucket"),
                           ("failovers", "streaming sessions re-homed "
                                         "after a replica loss"),
                           ("replay_tokens", "tokens re-prefilled by "
                                             "failover replay"),
                           ("replicas_lost", ""), ("drains", ""),
                           ("drain_timeouts", ""),
                           ("route_faults", ""),
                           ("scale_up_signals", ""),
                           ("scale_down_signals", "")):
            page.add("mxnet_router_%s_total" % key, st.get(key),
                     labels=lab, kind="counter", help_=help_)
        page.add("mxnet_router_replicas_up", st.get("replicas_up"),
                 labels=lab, help_="replicas taking new sessions")
        page.add("mxnet_router_queued", st.get("queued"), labels=lab,
                 help_="sessions waiting in tenant queues")
        page.add("mxnet_router_sessions", st.get("sessions"),
                 labels=lab, help_="streaming sessions bound to "
                                   "replicas now")
        for rep in st.get("replicas") or ():
            rlab = dict(lab, replica=rep.get("name") or "?")
            page.add("mxnet_router_replica_outstanding_tokens",
                     rep.get("outstanding"), labels=rlab,
                     help_="tokens owed by sessions bound to the "
                           "replica (the dispatch signal)")
            page.add("mxnet_router_replica_sessions",
                     rep.get("sessions"), labels=rlab)
        for name, t in (st.get("tenants") or {}).items():
            tlab = dict(lab, tenant=name)
            page.add("mxnet_router_tenant_queued", t.get("queued"),
                     labels=tlab)
            page.add("mxnet_router_tenant_throttled_total",
                     t.get("throttled"), labels=tlab, kind="counter")
            page.add("mxnet_router_tenant_shed_total", t.get("shed"),
                     labels=tlab, kind="counter")
            for q in ("p50", "p99"):
                page.add("mxnet_router_tenant_latency_ms",
                         (t.get("latency_ms") or {}).get(q),
                         labels=dict(tlab, quantile=q),
                         help_="session completion latency (submit "
                               "-> done)")
        for q in ("p50", "p99"):
            page.add("mxnet_router_failover_resume_ms",
                     (st.get("failover_resume_ms") or {}).get(q),
                     labels=dict(lab, quantile=q),
                     help_="replica-loss detection to first resumed "
                           "token")


def _render_usage(page):
    """Per-tenant cost attribution from the process meter
    (``mxnet_tpu.metering``): attributed tokens/FLOPs/page*seconds,
    prefix-cache credits, outcome counts, and the dual-entry
    reconciliation verdict — one gauge the alerting layer can page on
    when the books stop balancing."""
    from . import metering
    st = metering.snapshot()
    if st is None:
        return
    lab = {"meter": st.get("name") or "default"}
    for key, help_ in (("admitted", "usage records opened"),
                       ("dispatched", ""), ("closed", ""),
                       ("throttle_events", "")):
        page.add("mxnet_usage_%s_total" % key, st.get(key),
                 labels=lab, kind="counter", help_=help_)
    page.add("mxnet_usage_open", st.get("open"), labels=lab,
             help_="requests admitted but not yet closed")
    rec = st.get("reconcile") or {}
    page.add("mxnet_usage_reconciled", 1 if rec.get("ok") else 0,
             labels=lab, help_="1 while sum-over-tenants equals the "
                               "meter totals for every conserved "
                               "quantity")
    for name, t in sorted((st.get("tenants") or {}).items()):
        tlab = dict(lab, tenant=name)
        for key, help_ in (
                ("prompt_tokens", "prompt tokens attributed"),
                ("generated_tokens", "generated tokens attributed"),
                ("replay_tokens", "failover re-prefill tokens billed "
                                  "(exactly once, to the surviving "
                                  "replica)"),
                ("replay_cached_tokens", ""),
                ("prefix_hit_tokens", "tokens credited back by "
                                      "prefix-cache sharing"),
                ("prefix_bytes_saved", ""),
                ("throttle_events", "")):
            page.add("mxnet_usage_tenant_%s_total" % key, t.get(key),
                     labels=tlab, kind="counter", help_=help_)
        page.add("mxnet_usage_tenant_flops_total", t.get("flops"),
                 labels=tlab, kind="counter",
                 help_="attributed FLOPs (batch-share of each "
                       "dispatched program's cost_analysis)")
        page.add("mxnet_usage_tenant_page_seconds_total",
                 t.get("page_seconds"), labels=tlab, kind="counter",
                 help_="KV page*seconds integrated at decode step "
                       "boundaries")
        for outcome, n in sorted((t.get("outcomes") or {}).items()):
            page.add("mxnet_usage_tenant_outcomes_total", n,
                     labels=dict(tlab, outcome=outcome),
                     kind="counter")


def _render_identity(page):
    """The fleet-join info gauge: constant 1 whose labels say WHO this
    process is — run id, rank, restart generation, jax/jaxlib versions
    — so any series scraped from this endpoint joins to its fleet
    coordinates with one ``group_left`` instead of per-series labels."""
    from . import telemetry, tracing
    import jax
    import jaxlib
    ident = tracing.process_identity()
    rep = telemetry.report()
    page.add("mxnet_identity_info", 1,
             labels={"run": (rep or {}).get("run_id") or "",
                     "rank": ident["rank"],
                     "generation": ident["gen"],
                     "jax": jax.__version__,
                     "jaxlib": jaxlib.__version__},
             help_="constant 1; the labels identify this process "
                   "(run id, rank, restart generation, jax versions)")


def render():
    """The whole ``/metrics`` page as Prometheus text exposition."""
    page = _Page()
    page.add("mxnet_up", 1, help_="the mxnet_tpu process is alive")
    _render_identity(page)
    _render_training(page)
    _render_counters(page)
    _render_serving(page)
    _render_decode(page)
    _render_router(page)
    _render_usage(page)
    return page.text()


# ---------------------------------------------------------------------------
# the HTTP endpoint
# ---------------------------------------------------------------------------

def serve(port=None, host=None):
    """Start the ``/metrics`` endpoint on a daemon thread (idempotent
    — a second call returns the live port). ``port`` defaults to
    ``MXNET_METRICS_PORT``; 0 picks an ephemeral port. ``host``
    defaults to ``MXNET_METRICS_HOST`` or ``127.0.0.1`` — localhost
    by default on purpose. Returns the bound port."""
    global _http
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    with _http_lock:
        if _http is not None:
            return _http[0].server_address[1]
        if port is None:
            port = envs.get_int("MXNET_METRICS_PORT")
        if host is None:
            host = envs.get_str("MXNET_METRICS_HOST") or "127.0.0.1"

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = render().encode("utf-8")
                except Exception as exc:      # noqa: BLE001 — a render
                    # bug must surface as a 500, never kill the server
                    self.send_error(500, explain=str(exc)[:200])
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):       # scrapes are not news
                pass

        httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        httpd.daemon_threads = True
        thread = threading.Thread(target=httpd.serve_forever,
                                  name="mxnet-metrics", daemon=True)
        thread.start()
        _http = (httpd, thread)
        return httpd.server_address[1]


def server_port():
    """The live endpoint's port, or None when not serving."""
    with _http_lock:
        return _http[0].server_address[1] if _http else None


def stop_server():
    """Shut the endpoint down (tests; production just lets the daemon
    thread die with the process)."""
    global _http
    with _http_lock:
        pair, _http = _http, None
    if pair is not None:
        pair[0].shutdown()
        pair[0].server_close()
        pair[1].join(timeout=5)


# ---------------------------------------------------------------------------
# the SLO watchdog
# ---------------------------------------------------------------------------

class Watchdog:
    """Rolling-baseline SLO detector. Observes step records and
    cumulative serving snapshots (installed as telemetry's
    ``_watch_step``/``_watch_serving`` hooks) and emits one structured
    ``alert`` telemetry record + one warning per alert kind:

    - ``step_time_drift`` — recent-window step-time p50 above
      ``MXNET_WATCHDOG_DRIFT`` (default 1.5) x the rolling baseline
      p50 for ``MXNET_WATCHDOG_SUSTAIN`` (default 10) consecutive
      steps. The baseline (``MXNET_WATCHDOG_BASELINE`` steps, default
      50) only absorbs samples while no breach is building, so a
      regression cannot slowly become the new normal.
    - ``serving_shed_rate`` — sheds/submits over the snapshot delta
      above ``MXNET_WATCHDOG_SHED_RATE`` (default 0.3) once at least
      ``MXNET_WATCHDOG_MIN_REQUESTS`` (default 20) new requests
      arrived.
    - ``serving_queue_full`` — admission queue depth at or above 90%
      of its bound (``MXNET_WATCHDOG_QUEUE_FRAC``).
    - ``replica_skew`` — slowest replica's mean batch service time
      above ``MXNET_WATCHDOG_SKEW`` (default 2.0) x the replica
      median, each replica having served ≥3 batches — the straggler
      primitive.

    Serving baselines are kept per server (snapshots carry the server
    name), and the serving conditions alert on the healthy→breached
    edge with hysteresis: a breach that persists across snapshots
    emits ONE alert record, re-arming only when it clears. The
    telemetry alert list is additionally bounded at the sink.
    """

    def __init__(self):
        self.drift = max(1.01, envs.get_float("MXNET_WATCHDOG_DRIFT"))
        self.window = max(2, envs.get_int("MXNET_WATCHDOG_WINDOW"))
        self.baseline_n = max(
            2, envs.get_int("MXNET_WATCHDOG_BASELINE"))
        self.sustain = max(1, envs.get_int("MXNET_WATCHDOG_SUSTAIN"))
        self.shed_rate = envs.get_float("MXNET_WATCHDOG_SHED_RATE")
        self.min_requests = max(
            1, envs.get_int("MXNET_WATCHDOG_MIN_REQUESTS"))
        self.queue_frac = envs.get_float("MXNET_WATCHDOG_QUEUE_FRAC")
        self.skew = max(1.01, envs.get_float("MXNET_WATCHDOG_SKEW"))
        self._baseline = deque(maxlen=self.baseline_n)
        self._recent = deque(maxlen=self.window)
        self._breach = 0
        self._prev_serving = {}   # per-server previous snapshot
        self._fired = {}          # kind -> count (warn once per kind)
        # serving conditions re-arm instead of re-firing: a breach
        # alerts once on entry, then stays silent until it CLEARS —
        # keys are (kind, server)
        self._active = set()
        # RLock: on_serving holds it across its read-modify-write of
        # the previous snapshot (every replica worker thread can emit
        # a serving record concurrently) and _fire re-enters it
        self._lock = threading.RLock()

    # -- alert plumbing ----------------------------------------------------
    def _fire(self, kind, message, **fields):
        with self._lock:
            first = kind not in self._fired
            self._fired[kind] = self._fired.get(kind, 0) + 1
        from . import profiler, telemetry
        rec = {"kind": kind, "message": message}
        rec.update(fields)
        telemetry.alert_event(rec)
        profiler.increment_counter("watchdog_alerts")
        if first:
            warnings.warn("watchdog: %s — %s" % (kind, message))

    def alerts(self):
        with self._lock:
            return dict(self._fired)

    # -- step SLO ----------------------------------------------------------
    def on_step(self, rec):
        dur = rec.get("dur_ms")
        if dur is None:
            return
        from .telemetry import percentile
        with self._lock:
            self._on_step_locked(dur, percentile)

    def _on_step_locked(self, dur, percentile):
        if len(self._baseline) < self.baseline_n:
            self._baseline.append(dur)
            return
        self._recent.append(dur)
        if len(self._recent) < self.window:
            return
        base_p50 = percentile(self._baseline, 50)
        recent_p50 = percentile(self._recent, 50)
        if base_p50 and recent_p50 > self.drift * base_p50:
            self._breach += 1
            if self._breach == self.sustain:
                self._fire(
                    "step_time_drift",
                    "step-time p50 %.3f ms vs rolling baseline %.3f "
                    "ms (x%.2f > x%.2f) sustained %d steps"
                    % (recent_p50, base_p50, recent_p50 / base_p50,
                       self.drift, self.sustain),
                    recent_p50_ms=round(recent_p50, 3),
                    baseline_p50_ms=round(base_p50, 3),
                    ratio=round(recent_p50 / base_p50, 3))
        else:
            # healthy sample: the rolling baseline may absorb it
            self._breach = 0
            self._baseline.append(dur)

    # -- serving SLOs ------------------------------------------------------
    def on_serving(self, st):
        with self._lock:
            self._on_serving_locked(st)

    def _edge(self, kind, server, in_breach):
        """Entry-edge detector with hysteresis: True only when the
        (kind, server) condition goes healthy→breached; a breach that
        persists across snapshots alerts once, then re-arms when it
        clears — a days-long breach must not emit thousands of
        identical alert records."""
        key = (kind, server)
        if in_breach:
            if key in self._active:
                return False
            self._active.add(key)
            return True
        self._active.discard(key)
        return False

    def _on_serving_locked(self, st):
        server = st.get("name") or "default"
        prev = self._prev_serving.get(server)
        d_req = None
        if prev is not None:
            d_req = st.get("requests", 0) - prev.get("requests", 0)
            d_shed = st.get("shed", 0) - prev.get("shed", 0)
            if d_req < 0:
                # cumulative counters never decrease within one
                # server lifetime, so a regression is either a
                # RESTARTED server reusing this label (counters back
                # near zero — re-seed, or the dead generation's
                # baseline blinds the check until the new one
                # out-counts it) or a slightly-stale OUT-OF-ORDER
                # snapshot from a racing replica worker (counters
                # just below the baseline — drop it; the newer
                # snapshot was already evaluated and the baseline
                # must not rewind)
                if st.get("requests", 0) * 2 < prev.get("requests",
                                                        0):
                    prev = d_req = None
                else:
                    return
        if prev is None:
            # first snapshot for this server (generation): the
            # cumulative counters span its whole pre-watchdog history
            # — seed the baseline without evaluating the rate, or a
            # long-recovered burst of sheds would fire a spurious
            # alert on arm
            self._prev_serving.pop(server, None)
            self._prev_serving[server] = {
                "requests": st.get("requests", 0),
                "shed": st.get("shed", 0)}
            # bound the per-server table in server-churning processes
            # (fresh labels accumulate); prune the evicted server's
            # hysteresis keys with it
            while len(self._prev_serving) > 128:
                old = next(iter(self._prev_serving))
                del self._prev_serving[old]
                self._active = {k for k in self._active
                                if k[1] != old}
        if d_req is not None and d_req >= self.min_requests:
            # baselines are PER SERVER (snapshots carry the server
            # name): one server's counters must never dilute
            # another's deltas. The baseline only advances when the
            # check actually RUNS — small per-snapshot deltas
            # accumulate until they clear min_requests instead of
            # being absorbed unevaluated — and counters only move
            # forward, so an out-of-order older snapshot (two replica
            # workers emitting concurrently) cannot rewind it.
            self._prev_serving[server] = {
                "requests": max(st.get("requests", 0),
                                prev.get("requests", 0)),
                "shed": max(st.get("shed", 0), prev.get("shed", 0))}
            breach = d_shed > 0 and d_shed / float(d_req) > \
                self.shed_rate
            if self._edge("serving_shed_rate", server, breach):
                self._fire(
                    "serving_shed_rate",
                    "server %s shed %d of %d requests (%.0f%% > "
                    "%.0f%%) since the previous snapshot — sustained "
                    "overload, raise capacity or shed earlier "
                    "upstream" % (server, d_shed, d_req,
                                  100.0 * d_shed / d_req,
                                  100.0 * self.shed_rate),
                    server=server, shed=d_shed, requests=d_req,
                    rate=round(d_shed / float(d_req), 4))
        bound = st.get("max_queue") or 0
        depth = st.get("queue_depth", 0)
        if bound and self._edge("serving_queue_full", server,
                                depth >= self.queue_frac * bound):
            self._fire(
                "serving_queue_full",
                "server %s admission queue depth %d at %.0f%% of "
                "bound %d — latency is queue-bound; sheds are "
                "imminent" % (server, depth, 100.0 * depth / bound,
                              bound),
                server=server, queue_depth=depth, max_queue=bound)
        service = st.get("replica_service_ms") or []
        batches = st.get("replica_batches") or []
        valid = [(i, ms) for i, ms in enumerate(service)
                 if ms is not None and i < len(batches)
                 and batches[i] >= 3]
        if len(valid) >= 2:
            from .telemetry import percentile
            med = percentile([ms for _, ms in valid], 50)
            worst_i, worst = max(valid, key=lambda kv: kv[1])
            breach = bool(med) and worst > self.skew * med
            if self._edge("replica_skew", server, breach):
                self._fire(
                    "replica_skew",
                    "server %s replica %d mean batch service %.3f ms "
                    "vs replica median %.3f ms (x%.2f > x%.2f) — "
                    "straggling device/host"
                    % (server, worst_i, worst, med, worst / med,
                       self.skew),
                    server=server, replica=worst_i,
                    service_ms=round(worst, 3),
                    median_ms=round(med, 3),
                    ratio=round(worst / med, 3))


def enable_watchdog():
    """Install a fresh watchdog as telemetry's step/serving hooks
    (re-arming any previously fired alerts). Returns it."""
    global _watchdog
    from . import telemetry
    wd = Watchdog()
    _watchdog = wd
    telemetry._watch_step = wd.on_step
    telemetry._watch_serving = wd.on_serving
    return wd


def disable_watchdog():
    global _watchdog
    from . import telemetry
    telemetry._watch_step = None
    telemetry._watch_serving = None
    _watchdog = None


def watchdog_enabled():
    return _watchdog is not None
