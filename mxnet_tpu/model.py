"""Model helpers: checkpoints + kvstore wiring (parity:
python/mxnet/model.py).

Checkpoint writes are atomic (write to ``*.tmp``, ``os.replace``) so a
preempted save never leaves a truncated param/symbol file behind, and
``load_latest_valid_checkpoint`` gives ``fit(resume_from_checkpoint=..)``
its scan-and-validate resume point (see README "Fault tolerance")."""
from __future__ import annotations

import logging
import os
import re

from collections import namedtuple

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params", "load_latest_valid_checkpoint"]

BatchEndParam = namedtuple('BatchEndParams',
                           ['epoch', 'nbatch', 'eval_metric', 'locals'])


def _create_kvstore(kvstore, num_device, arg_params):
    """Create kvstore + decide update_on_kvstore
    (reference: model.py:82)."""
    from . import kvstore as kvs
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and 'dist' not in kvstore and \
                'tpu' not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == 'local':
                max_size = max(np_arr.size
                               for np_arr in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError('kvstore must be KVStore, str or None')
    if kv is None:
        update_on_kvstore = False
    # worker-side update is the TPU-native default (SURVEY §5.8): the
    # optimizer fuses behind the allreduce inside the compiled step
    from . import envs
    update_on_kvstore = envs.get_bool("MXNET_UPDATE_ON_KVSTORE",
                                      bool(update_on_kvstore))
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """Init kvstore with shared weights (reference: model.py:121)."""
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore,
                              param_names):
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list is None or (isinstance(grad_list, list)
                                 and grad_list[0] is None):
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    updates = [[] for _ in range(num_device)]
    bucketed = _bucketed_exchange(grad_arrays, kvstore)
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if not isinstance(arg_list, list):
            arg_list, grad_list = [arg_list], [grad_list]
        if grad_list[0] is None:
            continue
        index = i
        if kvstore and not bucketed:
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)
            kvstore.pull(name, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updates[k].append((index * num_device + k, g, w))
    for dev_updates in updates:
        for upd in dev_updates:
            i, g, w = upd
            updater(i, g, w)


def _bucketed_exchange(grad_arrays, kvstore):
    """The ``MXNET_GRAD_OVERLAP=1`` eager gradient exchange: dense
    single-copy gradients go through the kvstore as size-capped concat
    buckets (``parallel.grad_sync.bucketed_kvstore_sync`` — one
    push/pull per bucket instead of per key, exact because concat and
    the store's elementwise sum commute). Returns True when the
    exchange already happened; multi-copy or sparse rosters return
    False and keep the per-key loop above."""
    if not kvstore:
        return False
    from .parallel import grad_sync
    if not grad_sync.overlap_enabled():
        return False
    items = []
    for i, grad_list in enumerate(grad_arrays):
        if not isinstance(grad_list, list):
            grad_list = [grad_list]
        if grad_list[0] is None:
            continue
        if len(grad_list) != 1:
            return False          # per-device copies need per-key sums
        items.append((i, grad_list[0]))
    return grad_sync.bucketed_kvstore_sync(kvstore, items)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Write prefix-symbol.json + prefix-%04d.params
    (reference: model.py:394). Both writes are atomic (Symbol.save and
    nd.save are write-then-rename underneath)."""
    if symbol is not None:
        symbol.save('%s-symbol.json' % prefix)
    save_dict = {('arg:%s' % k): v for k, v in arg_params.items()}
    save_dict.update({('aux:%s' % k): v for k, v in aux_params.items()})
    param_name = '%s-%04d.params' % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_params(prefix, epoch):
    """Load one epoch's parameters. A manifest checkpoint (the async
    sharded writer, ``mxnet_tpu.checkpoint``) is re-assembled from its
    checksummed shard files — torn artifacts raise instead of loading
    silently; a PR 1-era single file loads through the legacy path
    unchanged."""
    from . import checkpoint as ckpt
    if ckpt.load_manifest(prefix, epoch) is not None:
        save_dict = ckpt.load_arrays(prefix, epoch)
    else:
        save_dict = nd.load('%s-%04d.params' % (prefix, epoch))
        if any(ckpt._PIECE_SEP in k for k in save_dict):
            # piece keys mean a sharded save whose manifest never
            # landed (killed between shard and manifest writes):
            # loading shard 0 alone would silently drop parameters
            raise MXNetError(
                'checkpoint %s-%04d.params holds shard pieces but no '
                'manifest (torn sharded save)' % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(':', 1)
        if tp == 'arg':
            arg_params[name] = v
        if tp == 'aux':
            aux_params[name] = v
    return (arg_params, aux_params)


def load_checkpoint(prefix, epoch):
    """Load symbol + params (reference: model.py:424)."""
    symbol = sym.load('%s-symbol.json' % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return (symbol, arg_params, aux_params)


def list_checkpoint_epochs(prefix):
    """Epochs with a ``prefix-%04d.params`` file on disk, ascending.
    (\\d+, not \\d{4}: '%04d' grows past four digits at epoch 10000.)"""
    directory = os.path.dirname(prefix) or '.'
    base = os.path.basename(prefix)
    pat = re.compile(re.escape(base) + r'-(\d+)\.params$')
    if not os.path.isdir(directory):
        return []
    epochs = {int(m.group(1)) for f in os.listdir(directory)
              for m in [pat.match(f)] if m}
    return sorted(epochs)


def _validate_sibling_states(prefix, epoch):
    """A param file whose sibling optimizer-state file is corrupt must
    reject the whole epoch (resuming with params but silently fresh
    optimizer state is a trajectory change, not a resume). Missing
    states are fine — the save simply didn't include them. Manifest
    epochs checksum their states inside the manifest (verified by
    ``checkpoint.load_arrays`` during the load itself); this is the
    legacy-epoch equivalent (a full pickle parse), skipped when a
    manifest exists so the states file is not parsed twice."""
    import pickle
    from . import checkpoint as ckpt
    if ckpt.load_manifest(prefix, epoch) is not None:
        return
    states_file = '%s-%04d.states' % (prefix, epoch)
    if not os.path.isfile(states_file):
        return
    with open(states_file, 'rb') as src:
        pickle.loads(src.read())


def load_latest_valid_checkpoint(prefix):
    """Newest checkpoint under ``prefix`` that loads cleanly, as
    ``(epoch, arg_params, aux_params)``; corrupt or partial artifacts
    (a torn shard from a killed writer, a preempted non-atomic copy,
    a corrupt sibling optimizer-state file) reject the whole epoch
    with a warning and the scan falls back to the next older one.
    Manifest epochs (``mxnet_tpu.checkpoint``) are checksum-verified;
    legacy epochs are validated by loading. Returns None when nothing
    usable exists; :func:`latest_checkpoint_scan` additionally reports
    how many newer epochs were rejected (the rollback depth)."""
    found = latest_checkpoint_scan(prefix)
    return None if found is None else found[:3]


def latest_checkpoint_scan(prefix):
    """Like :func:`load_latest_valid_checkpoint` but returns
    ``(epoch, arg_params, aux_params, skipped_epochs)`` so the resume
    path can account a rollback (``fault.note_resume``) — the steps of
    every skipped newer epoch are lost work."""
    epochs = list_checkpoint_epochs(prefix)
    for pos, epoch in enumerate(reversed(epochs)):
        try:
            _validate_sibling_states(prefix, epoch)
            arg_params, aux_params = load_params(prefix, epoch)
            return (epoch, arg_params, aux_params, pos)
        except Exception as exc:
            logging.warning(
                'skipping corrupt/partial checkpoint %s-%04d '
                '(%s: %s)', prefix, epoch, type(exc).__name__, exc)
    return None
