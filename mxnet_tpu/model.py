"""Model helpers: checkpoints + kvstore wiring (parity:
python/mxnet/model.py).

Checkpoint writes are atomic (write to ``*.tmp``, ``os.replace``) so a
preempted save never leaves a truncated param/symbol file behind, and
``load_latest_valid_checkpoint`` gives ``fit(resume_from_checkpoint=..)``
its scan-and-validate resume point (see README "Fault tolerance")."""
from __future__ import annotations

import logging
import os
import re

from collections import namedtuple

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params", "load_latest_valid_checkpoint"]

BatchEndParam = namedtuple('BatchEndParams',
                           ['epoch', 'nbatch', 'eval_metric', 'locals'])


def _create_kvstore(kvstore, num_device, arg_params):
    """Create kvstore + decide update_on_kvstore
    (reference: model.py:82)."""
    from . import kvstore as kvs
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and 'dist' not in kvstore and \
                'tpu' not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == 'local':
                max_size = max(np_arr.size
                               for np_arr in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError('kvstore must be KVStore, str or None')
    if kv is None:
        update_on_kvstore = False
    # worker-side update is the TPU-native default (SURVEY §5.8): the
    # optimizer fuses behind the allreduce inside the compiled step
    import os
    update_on_kvstore = bool(int(os.environ.get(
        "MXNET_UPDATE_ON_KVSTORE", 1 if update_on_kvstore else 0)))
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    """Init kvstore with shared weights (reference: model.py:121)."""
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore,
                              param_names):
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list is None or (isinstance(grad_list, list)
                                 and grad_list[0] is None):
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None):
    updates = [[] for _ in range(num_device)]
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if not isinstance(arg_list, list):
            arg_list, grad_list = [arg_list], [grad_list]
        if grad_list[0] is None:
            continue
        index = i
        if kvstore:
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)
            kvstore.pull(name, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updates[k].append((index * num_device + k, g, w))
    for dev_updates in updates:
        for upd in dev_updates:
            i, g, w = upd
            updater(i, g, w)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Write prefix-symbol.json + prefix-%04d.params
    (reference: model.py:394). Both writes are atomic (Symbol.save and
    nd.save are write-then-rename underneath)."""
    if symbol is not None:
        symbol.save('%s-symbol.json' % prefix)
    save_dict = {('arg:%s' % k): v for k, v in arg_params.items()}
    save_dict.update({('aux:%s' % k): v for k, v in aux_params.items()})
    param_name = '%s-%04d.params' % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_params(prefix, epoch):
    save_dict = nd.load('%s-%04d.params' % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(':', 1)
        if tp == 'arg':
            arg_params[name] = v
        if tp == 'aux':
            aux_params[name] = v
    return (arg_params, aux_params)


def load_checkpoint(prefix, epoch):
    """Load symbol + params (reference: model.py:424)."""
    symbol = sym.load('%s-symbol.json' % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return (symbol, arg_params, aux_params)


def list_checkpoint_epochs(prefix):
    """Epochs with a ``prefix-%04d.params`` file on disk, ascending.
    (\\d+, not \\d{4}: '%04d' grows past four digits at epoch 10000.)"""
    directory = os.path.dirname(prefix) or '.'
    base = os.path.basename(prefix)
    pat = re.compile(re.escape(base) + r'-(\d+)\.params$')
    if not os.path.isdir(directory):
        return []
    epochs = {int(m.group(1)) for f in os.listdir(directory)
              for m in [pat.match(f)] if m}
    return sorted(epochs)


def load_latest_valid_checkpoint(prefix):
    """Newest checkpoint under ``prefix`` that loads cleanly, as
    ``(epoch, arg_params, aux_params)``; corrupt or partial param files
    (a preempted non-atomic writer, a torn copy) are skipped with a
    warning and the scan falls back to the next older epoch. Returns
    None when nothing usable exists."""
    for epoch in reversed(list_checkpoint_epochs(prefix)):
        try:
            arg_params, aux_params = load_params(prefix, epoch)
            return (epoch, arg_params, aux_params)
        except Exception as exc:
            logging.warning(
                'skipping corrupt/partial checkpoint %s-%04d.params '
                '(%s: %s)', prefix, epoch, type(exc).__name__, exc)
    return None
