"""Training callbacks (parity: python/mxnet/callback.py)."""
from __future__ import annotations

import logging
import math
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar", "LogValidationMetricsCallback"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def do_checkpoint(prefix, period=1):
    """Checkpoint-every-N-epochs callback (reference: callback.py:58)."""
    from .model import save_checkpoint
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info('Iter[%d] Batch[%d] Train-%s=%f',
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class Speedometer:
    """Throughput logger (reference: callback.py:129).

    With a telemetry run active (``mxnet_tpu.telemetry``), the speed
    comes from the run's own step records — the same ring buffer that
    feeds ``telemetry.report()`` — instead of a private wall clock, so
    the logged samples/sec and the run summary can never disagree. The
    private clock remains the fallback for loops without telemetry.

    With the compile watch active (``mxnet_tpu.compile_watch``) and
    utilization measured, the log line additionally carries the mean
    MFU over the window; with the watch off the output is unchanged."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.auto_reset = auto_reset

    def _speed(self):
        from . import telemetry
        speed = telemetry.recent_rate(self.frequent) \
            if telemetry.enabled() else None
        if speed is not None:
            return speed
        try:
            return self.frequent * self.batch_size / \
                (time.time() - self.tic)
        except ZeroDivisionError:
            return float('inf')

    def _mfu(self):
        """Mean MFU over the logging window when the compile watch has
        utilization records for this run; None (no output change)
        otherwise."""
        from . import compile_watch
        if not compile_watch.enabled():
            return None
        return compile_watch.recent_mfu(self.frequent)

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count

        if self.init:
            if count % self.frequent == 0:
                speed = self._speed()
                mfu = self._mfu()
                mfu_part = () if mfu is None else (100.0 * mfu,)
                mfu_fmt = "" if mfu is None else "\tMFU: %.2f%%"
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = 'Epoch[%d] Batch [%d-%d]\tSpeed: %.2f samples/sec'
                    msg += mfu_fmt
                    msg += '\t%s=%f' * len(name_value)
                    logging.info(msg, param.epoch, count - self.frequent,
                                 count, speed, *mfu_part,
                                 *sum(name_value, ()))
                else:
                    logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f "
                                 "samples/sec" + mfu_fmt, param.epoch,
                                 count, speed, *mfu_part)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = '=' * filled_len + '-' * (self.bar_len - filled_len)
        logging.info('[%s] %s%s\r', prog_bar, percents, '%')


class LogValidationMetricsCallback:
    def __call__(self, param):
        if not param.eval_metric:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info('Epoch[%d] Validation-%s=%f', param.epoch, name,
                         value)
