"""Test oracles (parity: python/mxnet/test_utils.py — the 2k-LoC helper
library the reference ships *inside* the package; kept here for the same
reason: user tests import it).

Implements the reference's key patterns (SURVEY §4): numeric-gradient
checking vs autograd, numpy-oracle forward/backward checks, a
cross-backend consistency oracle (interpreted/eager vs compiled/
symbolic — the TPU analogue of cpu-vs-gpu check_consistency), and
seeded reproducibility helpers.
"""
from __future__ import annotations

import os
import numpy as np

from . import envs
from .base import MXNetError
from .context import Context, cpu, current_context

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "same", "rand_ndarray", "rand_shape_2d",
           "rand_shape_3d", "rand_shape_nd", "check_numeric_gradient",
           "check_symbolic_forward", "check_symbolic_backward",
           "check_consistency", "simple_forward", "random_seed"]


def default_context():
    """Context switched by env MXNET_TEST_DEFAULT_CTX (reference
    test_utils.py:53 uses a global; env keeps suites device-portable)."""
    name = envs.get_str("MXNET_TEST_DEFAULT_CTX")
    if name:
        dev, _, idx = name.partition(":")
        return Context(dev, int(idx or 0))
    return current_context()


def set_default_context(ctx):
    Context._default_ctx.value = ctx


def same(a, b):
    return np.array_equal(a, b)


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    return np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=('a', 'b'),
                        equal_nan=False):
    from .ndarray import NDArray
    if isinstance(a, NDArray):
        a = a.asnumpy()
    if isinstance(b, NDArray):
        b = b.asnumpy()
    rtol = 1e-5 if rtol is None else rtol
    atol = 1e-20 if atol is None else atol
    if not np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan):
        index = np.unravel_index(
            np.argmax(np.abs(np.asarray(a) - np.asarray(b))),
            np.asarray(a).shape) if np.asarray(a).shape else ()
        raise AssertionError(
            "Items are not equal (rtol=%g, atol=%g):\n%s=%s\n%s=%s\n"
            "max abs err at %s" % (rtol, atol, names[0], a, names[1], b,
                                   index))


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None, **kwargs):
    from .ndarray import array
    dtype = dtype or "float32"
    data = np.random.uniform(-1, 1, size=shape).astype(dtype)
    if stype == "default":
        return array(data, ctx=ctx or default_context())
    from .ndarray import sparse as _sp
    density = 0.1 if density is None else density
    mask = np.random.uniform(0, 1, size=shape) < density
    data = data * mask
    return _sp.array_to_stype(data, stype, ctx=ctx or default_context())


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    from .ndarray import array
    ctx = ctx or default_context()
    inputs = {k: array(v, ctx=ctx) for k, v in inputs.items()}
    exe = sym.bind(ctx, inputs)
    exe.forward(is_train=is_train)
    outputs = [o.asnumpy() for o in exe.outputs]
    if len(outputs) == 1:
        outputs = outputs[0]
    return outputs


def check_numeric_gradient(sym, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, use_forward_train=True,
                           ctx=None, grad_stype_dict=None, dtype=np.float32):
    """Finite differences vs the compiled autodiff gradient
    (reference: test_utils.py:801)."""
    from .ndarray import array
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    location = {k: np.asarray(v, dtype=dtype) for k, v in location.items()}
    aux_states = {k: np.asarray(v, dtype=dtype)
                  for k, v in (aux_states or {}).items()}
    if grad_nodes is None:
        grad_nodes = [k for k in arg_names]

    # scalarize: sum(out * random_proj) so the head is a scalar
    proj_seed = np.random.RandomState(0)

    args = {k: array(v, ctx=ctx) for k, v in location.items()}
    grad_req = {k: ("write" if k in grad_nodes else "null")
                for k in arg_names}
    grads = {k: array(np.zeros_like(location[k]), ctx=ctx)
             for k in grad_nodes}
    exe = sym.bind(ctx, args, args_grad=grads, grad_req=grad_req,
                   aux_states={k: array(v, ctx=ctx)
                               for k, v in aux_states.items()})
    exe.forward(is_train=use_forward_train)
    projs = [proj_seed.uniform(-1, 1, size=o.shape).astype(dtype)
             for o in exe.outputs]
    out_grads = [array(p, ctx=ctx) for p in projs]
    exe.forward_backward(out_grads=out_grads, is_train=use_forward_train)
    sym_grads = {k: grads[k].asnumpy() for k in grad_nodes}

    def loss_at(loc):
        a = {k: array(v, ctx=ctx) for k, v in loc.items()}
        e = sym.bind(ctx, a, aux_states={k: array(v, ctx=ctx)
                                         for k, v in aux_states.items()})
        e.forward(is_train=use_forward_train)
        return sum(float(np.sum(o.asnumpy() * p))
                   for o, p in zip(e.outputs, projs))

    atol = atol if atol is not None else rtol
    for name in grad_nodes:
        base = {k: v.copy() for k, v in location.items()}
        num_grad = np.zeros_like(location[name])
        flat = base[name].reshape(-1)
        ng_flat = num_grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps
            f_plus = loss_at(base)
            flat[i] = orig - numeric_eps
            f_minus = loss_at(base)
            flat[i] = orig
            ng_flat[i] = (f_plus - f_minus) / (2 * numeric_eps)
        assert_almost_equal(num_grad, sym_grads[name], rtol=rtol, atol=atol,
                            names=("numeric_%s" % name, "autodiff_%s" % name))


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=None,
                           aux_states=None, ctx=None, dtype=np.float32):
    """Forward vs numpy oracle (reference: test_utils.py:939)."""
    from .ndarray import array
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    args = {k: array(np.asarray(v, dtype=dtype), ctx=ctx)
            for k, v in location.items()}
    aux = {k: array(np.asarray(v, dtype=dtype), ctx=ctx)
           for k, v in (aux_states or {}).items()}
    exe = sym.bind(ctx, args, aux_states=aux)
    exe.forward(is_train=False)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym.list_outputs()]
    for out, exp in zip(exe.outputs, expected):
        assert_almost_equal(out.asnumpy(), exp, rtol=rtol, atol=atol)
    return exe.outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None, dtype=np.float32):
    """Backward vs numpy oracle (reference: test_utils.py:1017)."""
    from .ndarray import array
    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(arg_names, expected))
    args = {k: array(np.asarray(v, dtype=dtype), ctx=ctx)
            for k, v in location.items()}
    grads = {k: array(np.zeros(np.asarray(location[k]).shape, dtype=dtype),
                      ctx=ctx) for k in expected}
    reqs = {k: (grad_req if k in expected else "null") for k in arg_names} \
        if isinstance(grad_req, str) else grad_req
    aux = {k: array(np.asarray(v, dtype=dtype), ctx=ctx)
           for k, v in (aux_states or {}).items()}
    exe = sym.bind(ctx, args, args_grad=grads, grad_req=reqs, aux_states=aux)
    ogs = [array(np.asarray(g, dtype=dtype), ctx=ctx) for g in (
        out_grads if isinstance(out_grads, (list, tuple)) else [out_grads])]
    exe.forward_backward(out_grads=ogs, is_train=True)
    for name, exp in expected.items():
        assert_almost_equal(grads[name].asnumpy(), exp, rtol=rtol, atol=atol,
                            names=("grad_%s" % name, "expected_%s" % name))
    return exe


def check_consistency(sym, ctx_list=None, scale=1.0, dtype=None,
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True, grad_req="write", **kwargs):
    """Cross-backend oracle (the reference's cpu-vs-gpu
    check_consistency, test_utils.py:1224): run the SAME graph
    symbolically (one compiled XLA program) on every context in
    ``ctx_list`` — e.g. ``[mx.cpu(), mx.tpu()]`` for the TPU test lane —
    plus eagerly (interpreted, per-op jit) on the first context, and
    compare all outputs against the first context's. With
    ``grad_req='write'`` (the reference default) the BACKWARD runs on
    every context too and every argument gradient is compared;
    ``grad_req='null'`` restores forward-only checking."""
    from .ndarray import array, zeros as nd_zeros, ones as nd_ones
    from . import autograd as ag
    ctx = ctx_list[0] if ctx_list else default_context()
    arg_names = sym.list_arguments()
    aux_names = sym.list_auxiliary_states()
    shapes = kwargs.get("shapes")
    if arg_params is None:
        arg_params = {n: np.random.normal(0, scale, size=s).astype(
            dtype or np.float32) for n, s in shapes.items()}
    else:
        arg_params = dict(arg_params)   # never mutate the caller's dict
    if aux_params is None:
        aux_params = {n: arg_params.pop(n) for n in aux_names
                      if n in arg_params}
    with_grad = grad_req == "write"

    def _bind(c):
        grads = {k: nd_zeros(np.shape(v), ctx=c, dtype=str(
            np.asarray(v).dtype)) for k, v in arg_params.items()} \
            if with_grad else None
        ex = sym.bind(
            c, {k: array(v, ctx=c) for k, v in arg_params.items()},
            args_grad=grads,
            grad_req={k: grad_req for k in arg_params}
            if with_grad else None,
            aux_states={k: array(v, ctx=c)
                        for k, v in aux_params.items()}
            if aux_params else None)
        return ex, grads

    def _run(c):
        ex, grads = _bind(c)
        outs = ex.forward(is_train=with_grad)
        g = {}
        if with_grad:
            ex.backward([nd_ones(o.shape, ctx=c,
                                 dtype=str(o.asnumpy().dtype))
                         for o in outs])
            g = {k: v.asnumpy() for k, v in grads.items()}
        return [o.asnumpy() for o in outs], g

    # symbolic path, per context — outputs AND gradients must agree
    sym_outs, sym_grads = _run(ctx)
    for other in (ctx_list or [])[1:]:
        outs_o, grads_o = _run(other)
        for ref_o, got_o in zip(sym_outs, outs_o):
            assert_almost_equal(ref_o, got_o, rtol=tol or 1e-4,
                                atol=tol or 1e-4,
                                names=(str(ctx), str(other)))
        for k in sym_grads:
            assert_almost_equal(sym_grads[k], grads_o[k],
                                rtol=tol or 1e-4, atol=tol or 1e-4,
                                names=("grad(%s)@%s" % (k, ctx),
                                       "grad(%s)@%s" % (k, other)))
    # eager path: interpret graph node by node via NDArray ops, under
    # the SAME mode as the symbolic leg (train when grads are checked —
    # invoke_nd derives __train__ from the autograd mode)
    from .symbol.symbol import _topo
    env = {}
    all_params = dict(arg_params, **aux_params)
    mode = ag.train_mode() if with_grad else ag.predict_mode()
    with mode:
        for node in sym._topo_nodes():
            if node.is_variable():
                env[(id(node), 0)] = array(all_params[node.name],
                                           ctx=ctx)
            else:
                from .ndarray.ndarray import invoke_nd
                ins = [env[(id(s), i)] for (s, i) in node.inputs]
                outs = invoke_nd(node.op, ins, dict(node.attrs))
                if not isinstance(outs, list):
                    outs = [outs]
                for i, o in enumerate(outs):
                    env[(id(node), i)] = o
    eager_outs = [env[(id(n), i)].asnumpy() for (n, i) in sym._outputs]
    tol = tol or 1e-4
    for s_o, e_o in zip(sym_outs, eager_outs):
        assert_almost_equal(s_o, e_o, rtol=tol, atol=tol,
                            names=("symbolic", "eager"))
    return sym_outs


class random_seed:
    """Seed scope printing repro info on failure (reference:
    tests/python/unittest/common.py with_seed)."""

    def __init__(self, seed=None):
        self._seed = seed

    def __enter__(self):
        from . import random as _r
        seed = self._seed if self._seed is not None \
            else np.random.randint(0, 2**31)
        self.seed = seed
        np.random.seed(seed)
        _r.seed(seed)
        return self

    def __exit__(self, etype, *args):
        if etype is not None:
            print("*** test failure seed: MXNET_TEST_SEED=%d ***" % self.seed)
