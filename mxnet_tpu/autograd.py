"""Imperative autograd (parity: python/mxnet/autograd.py + src/imperative/).

TPU-native design: recording builds a lightweight tape DAG over NDArray
handles (the role of ``Imperative::RecordOp`` + per-node ``AGInfo``,
reference include/mxnet/imperative.h:42). ``backward`` does NOT
interpret the graph node-by-node like the reference's ``RunGraph``
(imperative.cc:508); it linearizes the tape into a *program*, compiles
forward+vjp into ONE XLA computation via ``jax.vjp`` under ``jax.jit``,
and caches the compiled executable keyed on program structure — so a
training loop pays tracing cost once, like CachedOp's per-signature
cache (cached_op.cc SetForwardGraph).

Recorded input buffers are stashed on the tape (jax arrays are
immutable, so this is free) matching the reference's saved-input
semantics when handles are mutated later.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "get_symbol",
           "set_recording", "set_training", "Function"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    prev = _st().recording
    _st().recording = bool(is_record)
    return prev


def set_training(train_mode_):
    prev = _st().training
    _st().training = bool(train_mode_)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train_mode_):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode_
        self._prev_is_record = None
        self._prev_train_mode = None

    def __enter__(self):
        if self._enter_is_record is not None:
            self._prev_is_record = set_recording(self._enter_is_record)
        if self._enter_train_mode is not None:
            self._prev_train_mode = set_training(self._enter_train_mode)
        return self

    def __exit__(self, ptype, value, trace):
        if self._enter_is_record is not None:
            set_recording(self._prev_is_record)
        if self._enter_train_mode is not None:
            set_training(self._prev_train_mode)


def record(train_mode=True):
    """Scope for recording ops for autograd (reference: autograd.py:122)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Mark NDArrays as variables to compute gradient for
    (reference: autograd.py:197)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, g, req in zip(variables, gradients, grad_reqs):
        var.grad = g
        var._grad_req = req


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------

class _TapeNode:
    __slots__ = ("op", "attrs", "inputs", "input_values", "rng", "n_outputs")

    def __init__(self, op, attrs, inputs, input_values, rng, n_outputs):
        self.op = op
        self.attrs = attrs
        self.inputs = inputs            # list[NDArray] handles
        self.input_values = input_values  # recorded raw jax buffers
        self.rng = rng
        self.n_outputs = n_outputs


def _record_op(op, nattrs, inputs, outputs, rng):
    node = _TapeNode(op, nattrs, list(inputs),
                     [i._data for i in inputs], rng, len(outputs))
    for i, o in enumerate(outputs):
        o._tape_node = node
        o._tape_index = i


# ---------------------------------------------------------------------------
# Program extraction + compiled backward
# ---------------------------------------------------------------------------

def _collect_graph(heads):
    """Topo-order tape nodes reachable from heads; gather leaves/consts."""
    nodes: List[_TapeNode] = []
    visited = set()

    def dfs(node):
        if node is None or id(node) in visited:
            return
        visited.add(id(node))
        for h in node.inputs:
            dfs(h._tape_node)
        nodes.append(node)

    for h in heads:
        dfs(h._tape_node)
    return nodes


def _build_program(heads, nodes):
    """Linearize into (instructions, leaf_handles, const_values, rng_keys).

    Instruction: (op, attr_key_repr, tuple of bindings); binding is
    ('l', i) leaf, ('n', node_pos, out_idx), or ('c', i) constant.
    """
    from .ops.registry import attr_key
    node_pos = {id(n): i for i, n in enumerate(nodes)}
    leaf_ids: Dict[int, int] = {}
    leaves: List[Any] = []
    consts: List[Any] = []
    rngs: List[Any] = []
    instrs = []
    struct = []

    def leaf_slot(h):
        if id(h) not in leaf_ids:
            leaf_ids[id(h)] = len(leaves)
            leaves.append(h)
        return leaf_ids[id(h)]

    for n in nodes:
        bindings = []
        for h, rec_val in zip(n.inputs, n.input_values):
            src = h._tape_node
            if src is not None and id(src) in node_pos:
                bindings.append(("n", node_pos[id(src)], h._tape_index))
            elif h._grad_req != "null":
                bindings.append(("l", leaf_slot(h)))
            else:
                bindings.append(("c", len(consts)))
                consts.append(rec_val)
        rng_slot = None
        if n.op.needs_rng:
            rng_slot = len(rngs)
            rngs.append(n.rng)
        instrs.append((n.op, dict(n.attrs), tuple(bindings), rng_slot,
                       n.n_outputs))
        struct.append((n.op.name, attr_key(n.attrs), tuple(bindings),
                       rng_slot, n.n_outputs))

    head_refs = []
    for h in heads:
        if h._tape_node is not None and id(h._tape_node) in node_pos:
            head_refs.append(("n", node_pos[id(h._tape_node)], h._tape_index))
        elif h._grad_req != "null":
            head_refs.append(("l", leaf_slot(h)))
        else:
            raise MXNetError("cannot differentiate a head that was not "
                             "computed under autograd.record()")
    return (instrs, tuple(struct), tuple(head_refs), leaves, consts, rngs)


def _run_program(instrs, head_refs, leaf_vals, const_vals, rng_keys):
    results: List[Tuple] = []
    for op, attrs, bindings, rng_slot, n_out in instrs:
        vals = []
        for b in bindings:
            if b[0] == "l":
                vals.append(leaf_vals[b[1]])
            elif b[0] == "n":
                vals.append(results[b[1]][b[2]])
            else:
                vals.append(const_vals[b[1]])
        if rng_slot is not None:
            out = op.forward(attrs, *vals, rng=rng_keys[rng_slot])
        else:
            out = op.forward(attrs, *vals)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        results.append(tuple(out[:n_out]))
    heads = []
    for b in head_refs:
        heads.append(leaf_vals[b[1]] if b[0] == "l" else results[b[1]][b[2]])
    return tuple(heads)


_bwd_cache: Dict[Tuple, Any] = {}
_bwd_cache_lock = threading.Lock()


def _get_backward_fn(struct, instrs, head_refs):
    import hashlib

    import jax

    from . import compile_watch
    key = (struct, head_refs)
    fn = _bwd_cache.get(key)
    if fn is None:
        def fwd_bwd(leaf_vals, const_vals, rng_keys, cotangents):
            def f(lv):
                return _run_program(instrs, head_refs, lv, const_vals,
                                    rng_keys)
            outs, vjp_fn = jax.vjp(f, list(leaf_vals))
            grads, = vjp_fn(tuple(cotangents))
            return outs, grads
        # ``struct`` (op names + attr keys + bindings) IS the program
        # content this closure bakes in, so its digest makes the
        # persistent compile cache safe across processes: two tapes
        # with identical shapes but different ops cannot collide.
        # storm=False — each distinct tape is a new program by design
        # (specialization, not churn).
        token = hashlib.sha256(
            repr((struct, head_refs)).encode()).hexdigest()
        fn = compile_watch.jit(fwd_bwd, "autograd:backward",
                               statics=token[:16], storm=False,
                               cache_token=token)
        with _bwd_cache_lock:
            _bwd_cache[key] = fn
    return fn


def _prepare_program(heads):
    """Collect + linearize the tape under ``heads`` (shared by the
    first-order and create_graph backward paths)."""
    nodes = _collect_graph(heads)
    if not nodes and all(h._tape_node is None for h in heads):
        raise MXNetError("cannot call backward: no ops were recorded "
                         "(use autograd.record())")
    return _build_program(heads, nodes)


def _cotangents(heads, head_grads):
    """Raw jax cotangent buffers, defaulting to ones per head."""
    import jax.numpy as jnp
    if head_grads is None:
        return [jnp.ones(h.shape, h._data.dtype) for h in heads]
    return [jnp.ones(h.shape, h._data.dtype) if g is None else g._data
            for h, g in zip(heads, head_grads)]


def _do_backward(heads, head_grads):
    heads = list(heads)
    instrs, struct, head_refs, leaves, consts, rngs = \
        _prepare_program(heads)
    if not leaves:
        return [], []
    cots = _cotangents(heads, head_grads)
    fn = _get_backward_fn(struct, instrs, head_refs)
    _, grads = fn(tuple(l._data for l in leaves), tuple(consts),
                  tuple(rngs), tuple(cots))
    return leaves, grads


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t. marked variables and accumulate
    into their ``.grad`` (reference: autograd.py:243)."""
    leaves, grads = _do_backward(heads, head_grads)
    for leaf, g in zip(leaves, grads):
        if leaf.grad is None:
            continue
        if leaf._grad_req == "add":
            leaf.grad._set_data(leaf.grad._data + g)
        else:  # write
            leaf.grad._set_data(g)
        leaf._fresh_grad = True


_hgrad_cache: Dict[Tuple, Any] = {}
_hgrad_counter = [0]


def _backward_as_op(heads, head_grads):
    """Differentiate ``heads`` w.r.t. the tape leaves by invoking the
    whole vjp program as ONE recorded op — so the returned gradients
    are themselves on the tape and a second ``backward``/``grad`` runs
    ``jax.vjp`` over this op's forward, i.e. true higher-order autograd
    (reference: create_graph=True, python/mxnet/autograd.py:270).
    Returns (leaves, grad_NDArrays)."""
    import jax
    from .ndarray.ndarray import NDArray, invoke_nd
    from .ops.registry import OpDef

    heads = list(heads)
    instrs, struct, head_refs, leaves, consts, rngs = \
        _prepare_program(heads)
    if not leaves:
        return [], []
    n_l, n_c, n_r = len(leaves), len(consts), len(rngs)
    key = (struct, head_refs)
    with _bwd_cache_lock:
        opdef = _hgrad_cache.get(key)
    if opdef is None:
        def grad_fwd(attrs, *vals):
            lv = vals[:n_l]
            cv = list(vals[n_l:n_l + n_c])
            rv = list(vals[n_l + n_c:n_l + n_c + n_r])
            cots = vals[n_l + n_c + n_r:]

            def f(lv_):
                return _run_program(instrs, head_refs, list(lv_), cv, rv)

            _, vjp_fn = jax.vjp(f, tuple(lv))
            grads, = vjp_fn(tuple(cots))
            return tuple(grads)

        with _bwd_cache_lock:
            opdef = _hgrad_cache.get(key)       # double-checked: the
            if opdef is None:                   # name must stay unique
                _hgrad_counter[0] += 1
                opdef = OpDef(
                    "_backward_program%d" % _hgrad_counter[0], grad_fwd,
                    arg_names=tuple("in%d" % i
                                    for i in range(n_l + n_c + n_r
                                                   + len(heads))),
                    num_outputs=n_l)
                _hgrad_cache[key] = opdef

    cots = [NDArray(c, ctx=heads[0]._ctx)
            for c in _cotangents(heads, head_grads)]
    const_nds = [NDArray(c, ctx=heads[0]._ctx) for c in consts]
    rng_nds = [NDArray(r, ctx=heads[0]._ctx) for r in rngs]
    out = invoke_nd(opdef, list(leaves) + const_nds + rng_nds + cots, {})
    grads = out if isinstance(out, (list, tuple)) else [out]
    return leaves, list(grads)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients of heads w.r.t. variables; with
    ``create_graph=True`` the results stay on the tape for higher-order
    differentiation (reference: autograd.py:270)."""
    from .ndarray.ndarray import NDArray
    if isinstance(heads, NDArray):
        heads = [heads]
    if isinstance(variables, NDArray):
        variables = [variables]
        single = True
    else:
        single = False
    # temporarily mark
    prev = [(v._grad_req,) for v in variables]
    for v in variables:
        if v._grad_req == "null":
            v._grad_req = "write"
    hg = [head_grads] if isinstance(head_grads, NDArray) else head_grads
    if create_graph:
        leaves, grad_nds = _backward_as_op(heads, hg)
        gmap = {id(l): g for l, g in zip(leaves, grad_nds)}
    else:
        leaves, grads = _do_backward(heads, hg)
        gmap = {id(l): NDArray(g, ctx=l._ctx)
                for l, g in zip(leaves, grads)}
    out = []
    for v, pr in zip(variables, prev):
        if id(v) not in gmap:
            raise MXNetError("one of the variables does not participate in "
                             "the computation of heads")
        out.append(gmap[id(v)])
        v._grad_req = pr[0]
    return out[0] if single else out


def get_symbol(x):
    """Recover the Symbol tracing the computation of ``x``
    (reference: autograd.py:304)."""
    from .symbol.symbol import _symbol_from_tape
    return _symbol_from_tape(x)


class Function:
    """Custom differentiable function (reference: autograd.py:365).

    Round-1 scope: forward runs eagerly; backward is invoked on the host
    during tape replay via jax.pure_callback.
    """

    def __init__(self):
        self._used = False

    def forward(self, *inputs):
        raise NotImplementedError()

    def backward(self, *output_grads):
        raise NotImplementedError()

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        from .ops.registry import OpDef
        import jax

        outs = self.forward(*[i for i in inputs])
        single = not isinstance(outs, (list, tuple))
        out_list = [outs] if single else list(outs)

        if is_recording():
            func = self
            in_shapes = [(i.shape, i.dtype) for i in inputs]

            def fwd_raw(attrs, *vals):
                import jax.numpy as jnp

                @jax.custom_vjp
                def f(*v):
                    return tuple(o._data for o in out_list) if len(out_list) > 1 \
                        else out_list[0]._data

                def f_fwd(*v):
                    return f(*v), v

                def f_bwd(res, g):
                    gs = g if isinstance(g, tuple) else (g,)

                    def host_bwd(*host_gs):
                        import numpy as np
                        nd_gs = [NDArray(jnp.asarray(x)) for x in host_gs]
                        igrads = func.backward(*nd_gs)
                        if not isinstance(igrads, (list, tuple)):
                            igrads = [igrads]
                        return tuple(np.asarray(ig.asnumpy())
                                     for ig in igrads)

                    import jax.numpy as jnp
                    shapes = tuple(jax.ShapeDtypeStruct(s, d)
                                   for s, d in in_shapes)
                    out = jax.pure_callback(host_bwd, shapes, *gs)
                    return tuple(out)

                f.defvjp(f_fwd, f_bwd)
                return f(*vals)

            op = OpDef("_custom_function", fwd_raw,
                       arg_names=["in%d" % i for i in range(len(inputs))],
                       num_outputs=len(out_list))
            _record_op(op, {}, list(inputs), out_list, None)
        return outs
