"""Optimizers (parity: python/mxnet/optimizer/optimizer.py).

Each ``update`` dispatches to a fused XLA update op from
mxnet_tpu.ops.optimizer_ops where one exists (the reference's fused CUDA
update kernels, src/operator/optimizer_op.cc); the long tail is composed
from NDArray ops (still jit-fused per call).
"""
from __future__ import annotations

import math
import warnings

import numpy

from ..base import Registry, MXNetError
from ..ndarray import invoke_nd

__all__ = ["Optimizer", "SGD", "Signum", "FTML", "DCASGD", "NAG", "SGLD",
           "Adam", "AdaGrad", "AdaDelta", "RMSProp", "Ftrl", "Adamax",
           "Nadam", "LBSGD", "Test", "Updater", "get_updater", "register",
           "create"]

_REG: Registry = Registry("optimizer", case_sensitive=False)


def register(klass):
    _REG.register(klass.__name__)(klass)
    return klass


class Optimizer:
    """Base optimizer (reference: optimizer.py:37)."""

    def __init__(self, rescale_grad=1., param_idx2name=None, wd=0.,
                 clip_gradient=None, learning_rate=0.01,
                 lr_scheduler=None, sym=None, begin_num_update=0,
                 multi_precision=False, param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.aggregate_num = 0
        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict), \
            'param_idx2name should be a dict of param indexes to names.'
        self.idx2name = param_idx2name.copy()
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) if sym is not None else ()
        self.param_dict = param_dict if param_dict else {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    create_optimizer = staticmethod(lambda name, **kwargs: create(name,
                                                                  **kwargs))

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        weight_master_copy = None
        if self.multi_precision and weight.dtype == numpy.float16:
            weight_master_copy = weight.astype(numpy.float32)
            return (weight_master_copy,) + (self.create_state(index,
                                                              weight_master_copy),)
        if weight.dtype == numpy.float16 and not self.multi_precision:
            warnings.warn("Accumulating with float16 in optimizer can lead "
                          "to poor accuracy or slow convergence. Consider "
                          "using multi_precision=True option.")
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == numpy.float16:
            weight_master_copy = state[0]
            original_state = state[1]
            grad32 = grad.astype(numpy.float32)
            self.update(index, weight_master_copy, grad32, original_state)
            weight[:] = weight_master_copy.astype(weight.dtype)
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and '__lr_mult__' in attr[name]:
                    self.lr_mult[name] = float(attr[name]['__lr_mult__'])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            # biases/beta get no decay; weights AND norm-layer gammas
            # keep it (reference: optimizer.py set_wd_mult)
            if not (n.endswith('_weight') or n.endswith('_gamma')):
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and '__wd_mult__' in attr[name]:
                    self.wd_mult[name] = float(attr[name]['__wd_mult__'])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx],
                                  self.num_update)

    def _get_lrs(self, indices):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        lrs = [lr for _ in indices]
        for i, index in enumerate(indices):
            if index in self.param_dict:
                lrs[i] *= self.param_dict[index].lr_mult
            elif index in self.lr_mult:
                lrs[i] *= self.lr_mult[index]
            elif index in self.idx2name:
                lrs[i] *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lrs

    def _get_lr(self, index):
        return self._get_lrs([index])[0]

    def _get_wds(self, indices):
        wds = [self.wd for _ in indices]
        for i, index in enumerate(indices):
            if index in self.param_dict:
                wds[i] *= self.param_dict[index].wd_mult
            elif index in self.wd_mult:
                wds[i] *= self.wd_mult[index]
            elif index in self.idx2name:
                wds[i] *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wds

    def _get_wd(self, index):
        return self._get_wds([index])[0]

    def __getstate__(self):
        ret = self.__dict__.copy()
        return ret

    def __setstate__(self, state):
        self.__dict__.update(state)



def _lazy_row_update(op_name, weight, grad, states, attrs):
    """Row-lazy sparse update (reference: the row_sparse kernels in
    src/operator/optimizer_op.cc with ``lazy_update=True``): apply the
    dense update rule to ONLY the rows named by the row_sparse gradient.
    Untouched rows — and their optimizer states — receive no update at
    all (no weight decay, no momentum decay), which is the semantic the
    reference documents for lazy sparse training.

    Lowering: gather the touched rows of weight and states, run the
    same registered update op on the row block, scatter back — the
    TPU-friendly form of the reference's per-row kernel loop.
    """
    import jax.numpy as jnp
    from ..ops import registry as _R
    op = _R.get_op(op_name)
    nattrs = _R.normalize_attrs(op, attrs)
    idx = grad.indices._data
    w = weight._data
    w_rows = jnp.take(w, idx, axis=0)
    st_rows = [jnp.take(s._data, idx, axis=0) for s in states]
    out = op.forward(nattrs, w_rows, grad.data._data, *st_rows)
    if not isinstance(out, (tuple, list)):
        out = (out,)
    weight._set_data(w.at[idx].set(out[0]))
    for s, ns in zip(states, out[1:]):
        s._set_data(s._data.at[idx].set(ns))


def _rsp_grad(grad):
    from ..ndarray.sparse import RowSparseNDArray
    return grad if isinstance(grad, RowSparseNDArray) else None


def _fp32_state(weight):
    """fp32 accumulator zeros on the weight's own placement — these
    optimizers keep fp32 state regardless of weight dtype (matching the
    reference, whose ndarray.zeros defaults to float32)."""
    return weight.zeros_like().astype(numpy.float32)

def _common_kwargs(opt, lr, wd):
    kw = {"lr": lr, "wd": wd, "rescale_grad": opt.rescale_grad}
    if opt.clip_gradient is not None:
        kw["clip_gradient"] = opt.clip_gradient
    return kw


@register
class SGD(Optimizer):
    """SGD with momentum and multi-precision
    (reference: optimizer.py:498)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return weight.zeros_like()

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype == numpy.float16:
            w32 = weight.astype(numpy.float32)
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = _common_kwargs(self, lr, wd)
        rsp = _rsp_grad(grad)
        if rsp is not None:
            if not self.lazy_update:
                grad = rsp.tostype("default")
            elif self.momentum != 0.0:
                return _lazy_row_update("sgd_mom_update", weight, rsp,
                                        [state],
                                        dict(kw, momentum=self.momentum))
            else:
                return _lazy_row_update("sgd_update", weight, rsp, [], kw)
        if self.momentum != 0.0:
            invoke_nd("sgd_mom_update", [weight, grad, state],
                      dict(kw, momentum=self.momentum), out=weight)
        else:
            invoke_nd("sgd_update", [weight, grad], kw, out=weight)

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == numpy.float16:
            self._update_count(index)
            lr = self._get_lr(index)
            wd = self._get_wd(index)
            kw = _common_kwargs(self, lr, wd)
            mom, w32 = state if isinstance(state, tuple) else (None, state)
            if self.momentum != 0.0:
                invoke_nd("mp_sgd_mom_update", [weight, grad, mom, w32],
                          dict(kw, momentum=self.momentum), out=weight)
            else:
                invoke_nd("mp_sgd_update", [weight, grad, w32], kw,
                          out=weight)
        else:
            self.update(index, weight, grad, state)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return weight.zeros_like()

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = _common_kwargs(self, lr, wd)
        if state is not None:
            invoke_nd("signum_update", [weight, grad, state],
                      dict(kw, momentum=self.momentum, wd_lh=self.wd_lh),
                      out=weight)
        else:
            invoke_nd("signsgd_update", [weight, grad], kw, out=weight)


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (weight.zeros_like(),
                weight.zeros_like(),
                weight.zeros_like())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        kw = _common_kwargs(self, lr, wd)
        d, v, z = state
        invoke_nd("ftml_update", [weight, grad, d, v, z],
                  dict(kw, beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, t=t), out=weight)


@register
class DCASGD(Optimizer):
    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (weight.zeros_like(),
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        mom, previous_weight = state
        d = grad + wd * weight + self.lamda * grad * grad * \
            (weight - previous_weight)
        if mom is not None:
            mom[:] = self.momentum * mom - lr * d
            update = mom
        else:
            update = -lr * d
        previous_weight[:] = weight
        weight[:] = weight + update


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return weight.zeros_like()

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = _common_kwargs(self, lr, wd)
        if state is not None:
            invoke_nd("nag_mom_update", [weight, grad, state],
                      dict(kw, momentum=self.momentum), out=weight)
        else:
            invoke_nd("sgd_update", [weight, grad], kw, out=weight)


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics."""

    def update(self, index, weight, grad, state):
        from ..ndarray import random as nd_random
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        noise = nd_random.normal(0, math.sqrt(lr), shape=weight.shape,
                                 dtype=weight.dtype, ctx=weight.context)
        weight[:] = weight - lr / 2 * (grad + wd * weight) + noise


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (weight.zeros_like(),
                weight.zeros_like())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        kw = _common_kwargs(self, lr, wd)
        mean, var = state
        kw_adam = dict(kw, beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon)
        rsp = _rsp_grad(grad)
        if rsp is not None:
            if self.lazy_update:
                return _lazy_row_update("adam_update", weight, rsp,
                                        [mean, var], kw_adam)
            grad = rsp.tostype("default")
        invoke_nd("adam_update", [weight, grad, mean, var], kw_adam,
                  out=weight)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return weight.zeros_like()

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = dict(_common_kwargs(self, lr, wd),
                  epsilon=self.float_stable_eps)
        rsp = _rsp_grad(grad)
        if rsp is not None:
            # reference sparse adagrad is always row-lazy
            return _lazy_row_update("adagrad_update", weight, rsp,
                                    [state], kw)
        invoke_nd("adagrad_update", [weight, grad, state], kw, out=weight)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_fp32_state(weight),
                _fp32_state(weight))

    def update(self, index, weight, grad, state):
        from ..ndarray import sqrt as nd_sqrt
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g[:] = self.rho * acc_g + (1. - self.rho) * grad * grad
        current_delta = ((acc_delta + self.epsilon).sqrt()
                         / (acc_g + self.epsilon).sqrt()) * grad
        acc_delta[:] = self.rho * acc_delta + \
            (1. - self.rho) * current_delta * current_delta
        weight[:] = weight - current_delta - wd * weight


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_fp32_state(weight),
                    _fp32_state(weight),
                    _fp32_state(weight))
        return _fp32_state(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = _common_kwargs(self, lr, wd)
        if not self.centered:
            invoke_nd("rmsprop_update", [weight, grad, state],
                      dict(kw, gamma1=self.gamma1, epsilon=self.epsilon),
                      out=weight)
        else:
            n, g, delta = state
            invoke_nd("rmspropalex_update", [weight, grad, n, g, delta],
                      dict(kw, gamma1=self.gamma1, gamma2=self.gamma2,
                           epsilon=self.epsilon), out=weight)
        if self.clip_weights:
            weight[:] = weight.clip(-self.clip_weights, self.clip_weights)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (_fp32_state(weight),
                _fp32_state(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = dict(_common_kwargs(self, lr, wd),
                  lamda1=self.lamda1, beta=self.beta)
        z, n = state
        rsp = _rsp_grad(grad)
        if rsp is not None:
            # reference sparse ftrl is row-lazy
            return _lazy_row_update("ftrl_update", weight, rsp, [z, n], kw)
        invoke_nd("ftrl_update", [weight, grad, z, n], kw, out=weight)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (_fp32_state(weight),
                _fp32_state(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1. - self.beta1 ** t)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        m_t, u_t = state
        m_t[:] = self.beta1 * m_t + (1. - self.beta1) * grad
        from ..ndarray import maximum as nd_maximum
        u_t[:] = nd_maximum(self.beta2 * u_t, grad.abs())
        weight[:] = weight - lr * m_t / (u_t + 1e-8)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.

    def create_state(self, index, weight):
        return (_fp32_state(weight),
                _fp32_state(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1. - 0.5 * (pow(0.96, t
                                                   * self.schedule_decay)))
        momentum_t_1 = self.beta1 * (1. - 0.5 * (pow(0.96, (t + 1)
                                                     * self.schedule_decay)))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        m_t[:] = self.beta1 * m_t + (1. - self.beta1) * grad
        v_t[:] = self.beta2 * v_t + (1. - self.beta2) * grad * grad
        grad_prime = grad / (1. - self.m_schedule)
        m_t_prime = m_t / (1. - m_schedule_next)
        v_t_prime = v_t / (1. - pow(self.beta2, t))
        m_t_bar = (1. - momentum_t) * grad_prime + momentum_t_1 * m_t_prime
        weight[:] = weight - lr * m_t_bar / \
            (v_t_prime.sqrt() + self.epsilon)


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style warmup (reference:
    optimizer.py LBSGD); implemented as layer-wise-scaled SGD."""

    def __init__(self, momentum=0.0, multi_precision=False, warmup_strategy
                 ='linear', warmup_epochs=5, batch_scale=1, updates_per_epoch
                 =32, begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(momentum=momentum,
                         multi_precision=multi_precision, **kwargs)
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch
        self.num_epochs = num_epochs


@register
class Test(Optimizer):
    """Test optimizer: w -= lr*grad (reference keeps one too)."""

    def create_state(self, index, weight):
        return _fp32_state(weight)

    def update(self, index, weight, grad, state):
        weight[:] = weight - self.lr * (grad * self.rescale_grad)


# aliases matching the reference registry
_REG.register("ccsgd", allow_override=True)(SGD)


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    cls = _REG.find(str(name))
    if cls is None:
        raise MXNetError("Cannot find optimizer %s" % name)
    return cls(**kwargs)


class Updater:
    """KVStore updater wrapper (reference: optimizer.py:1608)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = optimizer.aggregate_num > 0

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def sync_state_context(self, state, context):
        return state

    def set_states(self, states):
        import pickle
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        import pickle
        return pickle.dumps((self.states, self.optimizer)
                            if dump_optimizer else self.states)


def get_updater(optimizer):
    return Updater(optimizer)
