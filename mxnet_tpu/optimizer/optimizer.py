"""Optimizers (API parity: python/mxnet/optimizer/optimizer.py).

Own structure: per-index learning-rate/weight-decay scaling is one
table-resolution helper (``_scaled_all``); the eager preprocessing
shared by composed optimizers (rescale → clip → optional wd fold-in)
is ``_prepared_grad``; fused update rules dispatch to the XLA update
ops in mxnet_tpu.ops.optimizer_ops (the reference's fused kernels,
src/operator/optimizer_op.cc) while the long tail composes NDArray
ops. Row-lazy sparse updates gather/scatter only the touched rows
(``_lazy_row_update``).
"""
from __future__ import annotations

import math
import warnings

import numpy

from ..base import Registry, MXNetError
from ..ndarray import invoke_nd

__all__ = ["Optimizer", "SGD", "Signum", "FTML", "DCASGD", "NAG", "SGLD",
           "Adam", "AdaGrad", "AdaDelta", "RMSProp", "Ftrl", "Adamax",
           "Nadam", "LBSGD", "Test", "Updater", "get_updater", "register",
           "create"]

_REG: Registry = Registry("optimizer", case_sensitive=False)


def register(klass):
    _REG.register(klass.__name__)(klass)
    return klass


def _is_low_precision(dtype):
    """float16 OR bfloat16 (the MXU-native dtype) counts as low
    precision for master-weight purposes; the reference only knew fp16
    (optimizer.py multi_precision)."""
    return str(dtype) in ("float16", "bfloat16")


class Optimizer:
    """Base optimizer: per-index update counting, lr/wd multiplier
    tables, multi-precision plumbing (reference: optimizer.py:37)."""

    def __init__(self, rescale_grad=1., param_idx2name=None, wd=0.,
                 clip_gradient=None, learning_rate=0.01,
                 lr_scheduler=None, sym=None, begin_num_update=0,
                 multi_precision=False, param_dict=None):
        if param_idx2name is None:
            param_idx2name = {}
        if not isinstance(param_idx2name, dict):
            raise AssertionError(
                "param_idx2name should be a dict of param indexes to "
                "names.")
        self.rescale_grad, self.clip_gradient = rescale_grad, clip_gradient
        self.lr, self.wd = learning_rate, wd
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            lr_scheduler.base_lr = learning_rate
        self.begin_num_update = self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        self.aggregate_num = 0
        self.idx2name = dict(param_idx2name)
        self.sym_info = () if sym is None else \
            (sym.attr_dict(), sym.list_arguments())
        self.param_dict = param_dict or {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    create_optimizer = staticmethod(
        lambda name, **kwargs: create(name, **kwargs))

    # -- state ------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if _is_low_precision(weight.dtype):
            if self.multi_precision:
                master = weight.astype(numpy.float32)
                return (master, self.create_state(index, master))
            warnings.warn(
                "Accumulating with float16 in optimizer can lead to poor "
                "accuracy or slow convergence. Consider using "
                "multi_precision=True option.")
        return self.create_state(index, weight)

    # -- update protocol --------------------------------------------------
    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and _is_low_precision(weight.dtype):
            master, inner = state
            self.update(index, master, grad.astype(numpy.float32), inner)
            weight[:] = master.astype(weight.dtype)
        else:
            self.update(index, weight, grad, state)

    def master_from_state(self, weight, state):
        """The fp32 master NDArray inside one parameter's
        multi-precision state (the base-class ``(master, inner)``
        layout), or None when this weight has no master — the AMP
        checkpoint path (``amp.master_params``/``seed_masters``)
        reads and seeds masters through this accessor so it never
        hard-codes a state layout."""
        if self.multi_precision and _is_low_precision(weight.dtype) \
                and isinstance(state, tuple) and len(state) == 2:
            return state[0]
        return None

    # -- hyperparameter plumbing ------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning(
                "LRScheduler of the optimizer has already been defined.")
        self.lr = lr

    def _mults_from_sym(self, attr_key):
        table = {}
        if self.sym_info:
            attrs, arg_names = self.sym_info
            for name in arg_names:
                if name in attrs and attr_key in attrs[name]:
                    table[name] = float(attrs[name][attr_key])
        return table

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = self._mults_from_sym('__lr_mult__')
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        # biases/betas get no decay; weights and norm gammas keep it
        self.wd_mult = {n: 0.0 for n in self.idx2name.values()
                        if not n.endswith(('_weight', '_gamma'))}
        self.wd_mult.update(self._mults_from_sym('__wd_mult__'))
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        for idx in (index if isinstance(index, (list, tuple)) else [index]):
            count = self._index_update_count.get(idx,
                                                 self.begin_num_update) + 1
            self._index_update_count[idx] = count
            self.num_update = max(count, self.num_update)

    def _scaled_all(self, indices, base, mult_table, param_attr):
        """base value per index, scaled by (in priority order) the
        param_dict entry, the explicit multiplier table, or the
        name-keyed table via idx2name."""
        out = []
        for index in indices:
            scale = 1.0
            if index in self.param_dict:
                scale = getattr(self.param_dict[index], param_attr)
            elif index in mult_table:
                scale = mult_table[index]
            elif index in self.idx2name:
                scale = mult_table.get(self.idx2name[index], 1.0)
            out.append(base * scale)
        return out

    def _get_lrs(self, indices):
        base = self.lr if self.lr_scheduler is None else \
            self.lr_scheduler(self.num_update)
        return self._scaled_all(indices, base, self.lr_mult, 'lr_mult')

    def _get_wds(self, indices):
        return self._scaled_all(indices, self.wd, self.wd_mult, 'wd_mult')

    def _get_lr(self, index):
        return self._get_lrs([index])[0]

    def _get_wd(self, index):
        return self._get_wds([index])[0]

    def _step_inputs(self, index):
        """(lr, wd, base kwargs) for one index — the common preamble of
        every update()."""
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = {"lr": lr, "wd": wd, "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return lr, wd, kw

    def _prepared_grad(self, grad, wd=None, weight=None):
        """Eager-path preprocessing: rescale, clip, optionally fold wd."""
        grad = grad * self.rescale_grad
        if wd is not None:
            grad = grad + wd * weight
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        return grad

    # -- fused-step protocol (fused_step.py) ------------------------------
    def fused_step_fn(self, index, weight):
        """Pure functional update rule for the fused train step:
        ``fn(grad, weight, states, lr, wd, rescale) ->
        (new_weight, new_states)`` over raw jax arrays, where ``states``
        is the flat tuple of this index's state arrays and lr/wd/rescale
        arrive as traced scalars. Returns None when this optimizer has
        no compiled path; the executor then falls back to the eager
        loop. Implementations must mirror the registered eager update
        ops operation-for-operation so fused and eager steps are
        bit-identical. Multi-precision implementations (f32 master
        math under low-dtype weights) set ``fn.scalar_dtype =
        jnp.float32`` so the executor feeds them f32 scalars instead
        of grad-dtype casts — the eager mp ops apply python-float
        scalars to f32 arrays, and a bf16-cast lr would break the
        bit-identity contract."""
        return None

    def fused_step_scalars(self, index):
        """Host-side per-step ``(lr, wd)`` for one parameter — advances
        the update counters exactly like the eager ``_step_inputs``.
        Subclasses fold per-step corrections (Adam's bias correction)
        into the returned lr so the compiled program needs no step
        counter input."""
        self._update_count(index)
        return self._get_lr(index), self._get_wd(index)

    def fused_rollback_count(self, index):
        """Undo one ``fused_step_scalars`` count advance: the in-program
        guard skipped this parameter's update, and the eager path only
        counts applied updates."""
        c = self._index_update_count.get(index)
        if c is None:
            return
        self._index_update_count[index] = c - 1
        self.num_update = max([self.begin_num_update]
                              + list(self._index_update_count.values()))

    def fused_static_key(self):
        """Static hyperparameters baked into a compiled fused step —
        part of the compile-cache key, so mutating them mid-run
        compiles a fresh program instead of silently reusing stale
        constants."""
        return (type(self).__name__, self.clip_gradient)

    def __getstate__(self):
        return self.__dict__.copy()

    def __setstate__(self, state):
        self.__dict__.update(state)


# ---------------------------------------------------------------------------
# sparse row-lazy lowering
# ---------------------------------------------------------------------------

def _lazy_row_update(op_name, weight, grad, states, attrs):
    """Row-lazy sparse update (reference: the row_sparse kernels in
    src/operator/optimizer_op.cc with ``lazy_update=True``): apply the
    dense rule to ONLY the rows named by the row_sparse gradient;
    untouched rows and their states receive no update at all (no wd, no
    momentum decay) — the documented lazy sparse-training semantic.

    Lowering: gather touched rows of weight+states, run the registered
    update op on the row block, scatter back — the TPU-friendly form of
    the reference's per-row kernel loop.
    """
    import jax.numpy as jnp
    from ..ops import registry as _R
    op = _R.get_op(op_name)
    nattrs = _R.normalize_attrs(op, attrs)
    rows = grad.indices._data
    full = weight._data
    picked = [jnp.take(full, rows, axis=0)] + \
        [jnp.take(s._data, rows, axis=0) for s in states]
    out = op.forward(nattrs, picked[0], grad.data._data, *picked[1:])
    if not isinstance(out, (tuple, list)):
        out = (out,)
    weight._set_data(full.at[rows].set(out[0]))
    for s, updated in zip(states, out[1:]):
        s._set_data(s._data.at[rows].set(updated))


def _rsp_grad(grad):
    from ..ndarray.sparse import RowSparseNDArray
    return grad if isinstance(grad, RowSparseNDArray) else None


def _fp32_state(weight):
    """fp32 accumulator zeros on the weight's own placement — these
    optimizers keep fp32 state regardless of weight dtype (matching the
    reference, whose ndarray.zeros defaults to float32)."""
    return weight.zeros_like().astype(numpy.float32)


# ---------------------------------------------------------------------------
# fused-kernel optimizers
# ---------------------------------------------------------------------------

@register
class SGD(Optimizer):
    """SGD with momentum, lazy sparse rows, and multi-precision
    (reference: optimizer.py:498)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum, self.lazy_update = momentum, lazy_update

    def create_state(self, index, weight):
        return weight.zeros_like() if self.momentum != 0.0 else None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and _is_low_precision(weight.dtype):
            master = weight.astype(numpy.float32)
            return (self.create_state(index, master), master)
        return self.create_state(index, weight)

    def master_from_state(self, weight, state):
        # SGD's mp layout is (mom_or_None, master) — master LAST
        if self.multi_precision and _is_low_precision(weight.dtype) \
                and isinstance(state, tuple) and len(state) == 2:
            return state[1]
        return None

    def update(self, index, weight, grad, state):
        _, _, kw = self._step_inputs(index)
        rsp = _rsp_grad(grad)
        if rsp is not None:
            if not self.lazy_update:
                grad = rsp.tostype("default")
            elif self.momentum != 0.0:
                return _lazy_row_update("sgd_mom_update", weight, rsp,
                                        [state],
                                        dict(kw, momentum=self.momentum))
            else:
                return _lazy_row_update("sgd_update", weight, rsp, [], kw)
        if self.momentum != 0.0:
            invoke_nd("sgd_mom_update", [weight, grad, state],
                      dict(kw, momentum=self.momentum), out=weight)
        else:
            invoke_nd("sgd_update", [weight, grad], kw, out=weight)

    def update_multi_precision(self, index, weight, grad, state):
        if not (self.multi_precision and _is_low_precision(weight.dtype)):
            return self.update(index, weight, grad, state)
        _, _, kw = self._step_inputs(index)
        mom, master = state if isinstance(state, tuple) else (None, state)
        if self.momentum != 0.0:
            invoke_nd("mp_sgd_mom_update", [weight, grad, mom, master],
                      dict(kw, momentum=self.momentum), out=weight)
        else:
            invoke_nd("mp_sgd_update", [weight, grad, master], kw,
                      out=weight)

    def fused_step_fn(self, index, weight):
        """Mirrors ops/optimizer_ops.py sgd_update / sgd_mom_update
        (mp_sgd_update / mp_sgd_mom_update for multi-precision
        low-dtype weights: f32 master math, the low-dtype weight is a
        cast of the new master — states flat as [mom?, master], the
        :meth:`create_state_multi_precision` layout)."""
        import jax.numpy as jnp
        mu, clip = self.momentum, self.clip_gradient
        if self.multi_precision and _is_low_precision(weight.dtype):
            def fn(grad, weight, states, lr, wd, rescale):
                g = grad.astype(jnp.float32) * rescale
                if clip is not None and clip > 0:
                    g = jnp.clip(g, -clip, clip)
                if mu == 0.0:
                    (master,) = states
                    new_w32 = master - lr * (g + wd * master)
                    return new_w32.astype(weight.dtype), (new_w32,)
                mom, master = states
                new_mom = mu * mom - lr * (g + wd * master)
                new_w32 = master + new_mom
                return new_w32.astype(weight.dtype), (new_mom, new_w32)
            fn.scalar_dtype = jnp.float32
            return fn

        def fn(grad, weight, states, lr, wd, rescale):
            g = grad * rescale
            if clip is not None and clip > 0:
                g = jnp.clip(g, -clip, clip)
            if mu == 0.0:
                return weight - lr * (g + wd * weight), ()
            (mom,) = states
            new_mom = mu * mom - lr * (g + wd * weight)
            return weight + new_mom, (new_mom,)
        return fn

    def fused_static_key(self):
        return (type(self).__name__, self.clip_gradient, self.momentum)


@register
class Signum(Optimizer):
    """Sign-of-gradient SGD (reference: optimizer.py:728)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.wd_lh = momentum, wd_lh

    def create_state(self, index, weight):
        return weight.zeros_like() if self.momentum != 0.0 else None

    def update(self, index, weight, grad, state):
        _, _, kw = self._step_inputs(index)
        if state is None:
            invoke_nd("signsgd_update", [weight, grad], kw, out=weight)
        else:
            invoke_nd("signum_update", [weight, grad, state],
                      dict(kw, momentum=self.momentum, wd_lh=self.wd_lh),
                      out=weight)


@register
class FTML(Optimizer):
    """Follow-the-moving-leader (reference: optimizer.py:809)."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return tuple(weight.zeros_like() for _ in range(3))

    def update(self, index, weight, grad, state):
        _, _, kw = self._step_inputs(index)
        d, v, z = state
        invoke_nd("ftml_update", [weight, grad, d, v, z],
                  dict(kw, beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon,
                       t=self._index_update_count[index]), out=weight)


@register
class NAG(Optimizer):
    """Nesterov momentum (reference: optimizer.py:1026)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return weight.zeros_like() if self.momentum != 0.0 else None

    def update(self, index, weight, grad, state):
        _, _, kw = self._step_inputs(index)
        if state is None:
            invoke_nd("sgd_update", [weight, grad], kw, out=weight)
        else:
            invoke_nd("nag_mom_update", [weight, grad, state],
                      dict(kw, momentum=self.momentum), out=weight)


@register
class Adam(Optimizer):
    """Adam with bias correction folded into the step size
    (reference: optimizer.py:1148)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (weight.zeros_like(), weight.zeros_like())

    def update(self, index, weight, grad, state):
        lr, _, kw = self._step_inputs(index)
        t = self._index_update_count[index]
        kw["lr"] = lr * math.sqrt(1. - self.beta2 ** t) \
            / (1. - self.beta1 ** t)
        mean, var = state
        kw.update(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon)
        rsp = _rsp_grad(grad)
        if rsp is not None:
            if self.lazy_update:
                return _lazy_row_update("adam_update", weight, rsp,
                                        [mean, var], kw)
            grad = rsp.tostype("default")
        invoke_nd("adam_update", [weight, grad, mean, var], kw, out=weight)

    def fused_step_fn(self, index, weight):
        """Mirrors ops/optimizer_ops.py adam_update (wd folded into the
        gradient BEFORE the clip); ``lr`` arrives bias-corrected from
        :meth:`fused_step_scalars`. Multi-precision low-dtype weights
        run the base-class mp layout [master, mean, var]: the eager
        path's ``update(index, master, grad.astype(f32), inner)``
        operation-for-operation, weight = cast of the new master."""
        import jax.numpy as jnp
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        clip = self.clip_gradient
        if self.multi_precision and _is_low_precision(weight.dtype):
            def fn(grad, weight, states, lr, wd, rescale):
                master, mean, var = states
                g = grad.astype(jnp.float32) * rescale + wd * master
                if clip is not None and clip > 0:
                    g = jnp.clip(g, -clip, clip)
                new_mean = b1 * mean + (1 - b1) * g
                new_var = b2 * var + (1 - b2) * jnp.square(g)
                new_w32 = master - lr * new_mean / (jnp.sqrt(new_var)
                                                    + eps)
                return new_w32.astype(weight.dtype), \
                    (new_w32, new_mean, new_var)
            fn.scalar_dtype = jnp.float32
            return fn

        def fn(grad, weight, states, lr, wd, rescale):
            g = grad * rescale + wd * weight
            if clip is not None and clip > 0:
                g = jnp.clip(g, -clip, clip)
            mean, var = states
            new_mean = b1 * mean + (1 - b1) * g
            new_var = b2 * var + (1 - b2) * jnp.square(g)
            new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + eps)
            return new_w, (new_mean, new_var)
        return fn

    def fused_step_scalars(self, index):
        lr, wd = super().fused_step_scalars(index)
        t = self._index_update_count[index]
        lr = lr * math.sqrt(1. - self.beta2 ** t) / (1. - self.beta1 ** t)
        return lr, wd

    def fused_static_key(self):
        return (type(self).__name__, self.clip_gradient, self.beta1,
                self.beta2, self.epsilon)


@register
class AdaGrad(Optimizer):
    """Accumulated squared-gradient scaling (reference:
    optimizer.py:1280); sparse updates are always row-lazy."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return weight.zeros_like()

    def update(self, index, weight, grad, state):
        _, _, kw = self._step_inputs(index)
        kw["epsilon"] = self.float_stable_eps
        rsp = _rsp_grad(grad)
        if rsp is not None:
            return _lazy_row_update("adagrad_update", weight, rsp,
                                    [state], kw)
        invoke_nd("adagrad_update", [weight, grad, state], kw, out=weight)

    def fused_step_fn(self, index, weight):
        """Mirrors ops/optimizer_ops.py adagrad_update (mp low-dtype:
        base-class layout [master, history], f32 master math)."""
        import jax.numpy as jnp
        from ..ops.optimizer_ops import stable_sqrt
        eps, clip = self.float_stable_eps, self.clip_gradient
        if self.multi_precision and _is_low_precision(weight.dtype):
            def fn(grad, weight, states, lr, wd, rescale):
                master, history = states
                g = grad.astype(jnp.float32) * rescale
                if clip is not None and clip > 0:
                    g = jnp.clip(g, -clip, clip)
                new_h = history + jnp.square(g)
                new_w32 = master - lr * (g / stable_sqrt(new_h + eps)
                                         + wd * master)
                return new_w32.astype(weight.dtype), (new_w32, new_h)
            fn.scalar_dtype = jnp.float32
            return fn

        def fn(grad, weight, states, lr, wd, rescale):
            g = grad * rescale
            if clip is not None and clip > 0:
                g = jnp.clip(g, -clip, clip)
            (history,) = states
            new_h = history + jnp.square(g)
            new_w = weight - lr * (g / stable_sqrt(new_h + eps)
                                   + wd * weight)
            return new_w, (new_h,)
        return fn

    def fused_static_key(self):
        return (type(self).__name__, self.clip_gradient,
                self.float_stable_eps)


@register
class RMSProp(Optimizer):
    """Tieleman/Hinton (plain) or Graves (centered) variant
    (reference: optimizer.py:1347)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.epsilon, self.centered = epsilon, centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        n = 3 if self.centered else 1
        states = tuple(_fp32_state(weight) for _ in range(n))
        return states if self.centered else states[0]

    def update(self, index, weight, grad, state):
        _, _, kw = self._step_inputs(index)
        if self.centered:
            n, g, delta = state
            invoke_nd("rmspropalex_update", [weight, grad, n, g, delta],
                      dict(kw, gamma1=self.gamma1, gamma2=self.gamma2,
                           epsilon=self.epsilon), out=weight)
        else:
            invoke_nd("rmsprop_update", [weight, grad, state],
                      dict(kw, gamma1=self.gamma1, epsilon=self.epsilon),
                      out=weight)
        if self.clip_weights:
            weight[:] = weight.clip(-self.clip_weights, self.clip_weights)

    def fused_step_fn(self, index, weight):
        """Mirrors ops/optimizer_ops.py rmsprop_update /
        rmspropalex_update (wd folded pre-clip), plus the host-side
        clip_weights pass (mp low-dtype: base-class layout
        [master, n] / [master, n, g, delta], f32 master math)."""
        import jax.numpy as jnp
        from ..ops.optimizer_ops import stable_sqrt
        rho, mu, eps = self.gamma1, self.gamma2, self.epsilon
        clip, cw = self.clip_gradient, self.clip_weights
        centered = self.centered
        if self.multi_precision and _is_low_precision(weight.dtype):
            def fn(grad, weight, states, lr, wd, rescale):
                master = states[0]
                g = grad.astype(jnp.float32) * rescale + wd * master
                if clip is not None and clip > 0:
                    g = jnp.clip(g, -clip, clip)
                if not centered:
                    (n,) = states[1:]
                    new_n = rho * n + (1 - rho) * jnp.square(g)
                    new_w32 = master - lr * g / stable_sqrt(new_n + eps)
                    new_states = (new_n,)
                else:
                    n, g_acc, delta = states[1:]
                    new_n = rho * n + (1 - rho) * jnp.square(g)
                    new_g = rho * g_acc + (1 - rho) * g
                    new_delta = mu * delta - lr * g / stable_sqrt(
                        new_n - jnp.square(new_g) + eps)
                    new_w32 = master + new_delta
                    new_states = (new_n, new_g, new_delta)
                if cw:
                    new_w32 = jnp.clip(new_w32, -cw, cw)
                return new_w32.astype(weight.dtype), \
                    (new_w32,) + new_states
            fn.scalar_dtype = jnp.float32
            return fn

        def fn(grad, weight, states, lr, wd, rescale):
            g = grad * rescale + wd * weight
            if clip is not None and clip > 0:
                g = jnp.clip(g, -clip, clip)
            if not centered:
                (n,) = states
                new_n = rho * n + (1 - rho) * jnp.square(g)
                new_w = weight - lr * g / stable_sqrt(new_n + eps)
                new_states = (new_n,)
            else:
                n, g_acc, delta = states
                new_n = rho * n + (1 - rho) * jnp.square(g)
                new_g = rho * g_acc + (1 - rho) * g
                new_delta = mu * delta - lr * g / stable_sqrt(
                    new_n - jnp.square(new_g) + eps)
                new_w = weight + new_delta
                new_states = (new_n, new_g, new_delta)
            if cw:
                new_w = jnp.clip(new_w, -cw, cw)
            return new_w, new_states
        return fn

    def fused_static_key(self):
        return (type(self).__name__, self.clip_gradient, self.gamma1,
                self.gamma2, self.epsilon, self.centered,
                self.clip_weights)


@register
class Ftrl(Optimizer):
    """FTRL-proximal (reference: optimizer.py:1440); sparse updates are
    row-lazy."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (_fp32_state(weight), _fp32_state(weight))

    def update(self, index, weight, grad, state):
        _, _, kw = self._step_inputs(index)
        kw.update(lamda1=self.lamda1, beta=self.beta)
        z, n = state
        rsp = _rsp_grad(grad)
        if rsp is not None:
            return _lazy_row_update("ftrl_update", weight, rsp, [z, n], kw)
        invoke_nd("ftrl_update", [weight, grad, z, n], kw, out=weight)


# ---------------------------------------------------------------------------
# composed (NDArray-op) optimizers
# ---------------------------------------------------------------------------

@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py:778)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum, self.lamda = momentum, lamda
        self.weight_previous = {}

    def create_state(self, index, weight):
        mom = weight.zeros_like() if self.momentum != 0.0 else None
        return (mom, weight.copy())

    def update(self, index, weight, grad, state):
        lr, wd, _ = self._step_inputs(index)
        grad = self._prepared_grad(grad)
        mom, prev = state
        compensated = grad + wd * weight + \
            self.lamda * grad * grad * (weight - prev)
        if mom is None:
            step = -lr * compensated
        else:
            mom[:] = self.momentum * mom - lr * compensated
            step = mom
        prev[:] = weight
        weight[:] = weight + step


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics: SGD plus step-scaled
    Gaussian noise (reference: optimizer.py:1108)."""

    def update(self, index, weight, grad, state):
        from ..ndarray import random as nd_random
        lr, wd, _ = self._step_inputs(index)
        grad = self._prepared_grad(grad)
        noise = nd_random.normal(0, math.sqrt(lr), shape=weight.shape,
                                 dtype=weight.dtype, ctx=weight.context)
        weight[:] = weight - lr / 2 * (grad + wd * weight) + noise


@register
class AdaDelta(Optimizer):
    """Adaptive-delta with two squared accumulators
    (reference: optimizer.py:1500)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (_fp32_state(weight), _fp32_state(weight))

    def update(self, index, weight, grad, state):
        _, wd, _ = self._step_inputs(index)
        grad = self._prepared_grad(grad)
        sq_grad, sq_delta = state
        sq_grad[:] = self.rho * sq_grad + (1. - self.rho) * grad * grad
        delta = ((sq_delta + self.epsilon).sqrt()
                 / (sq_grad + self.epsilon).sqrt()) * grad
        sq_delta[:] = self.rho * sq_delta + (1. - self.rho) * delta * delta
        weight[:] = weight - delta - wd * weight


@register
class Adamax(Optimizer):
    """Infinity-norm Adam variant (reference: optimizer.py:1553)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (_fp32_state(weight), _fp32_state(weight))

    def update(self, index, weight, grad, state):
        from ..ndarray import maximum as nd_maximum
        lr, wd, _ = self._step_inputs(index)
        lr /= 1. - self.beta1 ** self._index_update_count[index]
        grad = self._prepared_grad(grad, wd, weight)
        m, u = state
        m[:] = self.beta1 * m + (1. - self.beta1) * grad
        u[:] = nd_maximum(self.beta2 * u, grad.abs())
        weight[:] = weight - lr * m / (u + 1e-8)


@register
class Nadam(Optimizer):
    """Adam with Nesterov momentum schedule
    (reference: optimizer.py:1591)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.

    def create_state(self, index, weight):
        return (_fp32_state(weight), _fp32_state(weight))

    def _momentum_at(self, t):
        return self.beta1 * (1. - 0.5 * 0.96 ** (t * self.schedule_decay))

    def update(self, index, weight, grad, state):
        lr, wd, _ = self._step_inputs(index)
        t = self._index_update_count[index]
        grad = self._prepared_grad(grad, wd, weight)
        mu_t, mu_next = self._momentum_at(t), self._momentum_at(t + 1)
        self.m_schedule *= mu_t
        schedule_next = self.m_schedule * mu_next
        m, v = state
        m[:] = self.beta1 * m + (1. - self.beta1) * grad
        v[:] = self.beta2 * v + (1. - self.beta2) * grad * grad
        g_hat = grad / (1. - self.m_schedule)
        m_hat = m / (1. - schedule_next)
        v_hat = v / (1. - self.beta2 ** t)
        blended = (1. - mu_t) * g_hat + mu_next * m_hat
        weight[:] = weight - lr * blended / (v_hat.sqrt() + self.epsilon)


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style warmup (reference:
    optimizer.py:856); implemented as layer-wise-scaled SGD."""

    def __init__(self, momentum=0.0, multi_precision=False,
                 warmup_strategy='linear', warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60,
                 **kwargs):
        super().__init__(momentum=momentum,
                         multi_precision=multi_precision, **kwargs)
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs, self.num_epochs = warmup_epochs, num_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch


@register
class Test(Optimizer):
    """Plain w -= lr*grad (the reference keeps one too)."""

    def create_state(self, index, weight):
        return _fp32_state(weight)

    def update(self, index, weight, grad, state):
        weight[:] = weight - self.lr * (grad * self.rescale_grad)


# registry aliases matching the reference
_REG.register("ccsgd", allow_override=True)(SGD)


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    cls = _REG.find(str(name))
    if cls is None:
        raise MXNetError("Cannot find optimizer %s" % name)
    return cls(**kwargs)


class Updater:
    """KVStore-side state bookkeeping around one Optimizer
    (reference: optimizer.py:1608).

    Every update funnels through ``__call__`` — Module, gluon Trainer
    and kvstore-hosted optimizers alike — so this is where the
    fault-tolerance layer sits: planned ``grad`` faults are injected
    and the non-finite gradient guard (skip_step / scale_backoff,
    ``mxnet_tpu.fault``) drops poisoned updates before they can reach
    the weights. Zero-cost straight-through path when no plan or guard
    policy is active."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = optimizer.aggregate_num > 0

    def __call__(self, index, grad, weight):
        from .. import fault
        if fault.is_enabled():
            grad, skip = fault.filter_gradient(index, grad)
            if skip:
                return
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def sync_state_context(self, state, context):
        return state

    def set_states(self, states):
        import pickle
        payload = pickle.loads(states)
        if isinstance(payload, tuple) and len(payload) == 2:
            self.states, self.optimizer = payload
        else:
            self.states = payload
        self.states_synced = dict.fromkeys(self.states, False)

    def get_states(self, dump_optimizer=False):
        import pickle
        return pickle.dumps((self.states, self.optimizer)
                            if dump_optimizer else self.states)


def get_updater(optimizer):
    return Updater(optimizer)
