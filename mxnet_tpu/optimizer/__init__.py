"""Optimizer package (parity: python/mxnet/optimizer/)."""
from .optimizer import (Optimizer, SGD, Signum, FTML, DCASGD, NAG, SGLD,
                        Adam, AdaGrad, AdaDelta, RMSProp, Ftrl, Adamax,
                        Nadam, LBSGD, Test, Updater, get_updater, register,
                        create)

opt_registry_create = create
