"""Generic class registry (parity: python/mxnet/registry.py — the
factory machinery behind ``mx.optimizer.register``/``create`` style
APIs, reimplemented over plain dicts)."""
from __future__ import annotations

import json
import warnings

__all__ = ["get_registry", "get_register_func", "get_alias_func",
           "get_create_func"]

_REGISTRY = {}


def get_registry(base_class):
    """A copy of the name -> class table for ``base_class``."""
    if base_class not in _REGISTRY:
        _REGISTRY[base_class] = {}
    return _REGISTRY[base_class].copy()


def get_register_func(base_class, nickname):
    """Build a registrator for subclasses of ``base_class``."""
    if base_class not in _REGISTRY:
        _REGISTRY[base_class] = {}
    registry = _REGISTRY[base_class]

    def register(klass, name=None):
        assert issubclass(klass, base_class), \
            "Can only register subclass of %s" % base_class.__name__
        if name is None:
            name = klass.__name__
        name = name.lower()
        if name in registry:
            warnings.warn(
                "New %s %s.%s registered with name %s is overriding "
                "existing %s %s.%s" % (
                    nickname, klass.__module__, klass.__name__, name,
                    nickname, registry[name].__module__,
                    registry[name].__name__),
                UserWarning, stacklevel=2)
        registry[name] = klass
        return klass

    register.__doc__ = "Register %s to the %s factory" % (nickname,
                                                          nickname)
    return register


def get_alias_func(base_class, nickname):
    """Registrator that records a class under several names."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass
        return reg
    return alias


def get_create_func(base_class, nickname):
    """Factory: ``create(name_or_instance, **kwargs)`` resolving names
    (or ``'{"name": ..., attr: ...}'`` JSON strings, the reference's
    serialized form) through the registry."""
    if base_class not in _REGISTRY:
        _REGISTRY[base_class] = {}
    registry = _REGISTRY[base_class]

    def create(*args, **kwargs):
        if len(args):
            name = args[0]
            args = args[1:]
        else:
            name = kwargs.pop(nickname)
        if isinstance(name, base_class):
            assert not args and not kwargs, (
                "%s is already an instance; additional arguments are "
                "invalid" % nickname)
            return name
        if isinstance(name, str) and name.startswith("{"):
            payload = json.loads(name)
            name = payload.pop("name")
            payload.update(kwargs)
            kwargs = payload
        assert isinstance(name, str), \
            "%s must be of string type" % nickname
        name = name.lower()
        assert name in registry, \
            "%s is not registered. Known: %s" % (
                name, sorted(registry))
        return registry[name](*args, **kwargs)

    create.__doc__ = "Create a %s instance from config" % nickname
    return create
