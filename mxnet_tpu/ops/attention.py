"""Fused attention on the registered-op surface.

SURVEY §5.7 requires the long-context extensions to be reachable from
the framework API, not only from ``mxnet_tpu.parallel``: these ops put
flash/ring/ulysses attention behind the same registry every other
operator uses, so Symbol graphs, NDArray eager calls, and Gluon
HybridBlocks (via ``F._contrib_flash_attention``) all reach them. The
reference's closest surface is the proposal-era multi-head attention
contrib ops (ref src/operator/contrib/transformer.cc); this framework
exposes the TPU-native kernels instead.

Inputs are (B, T, H, D). ``impl``:
- ``auto``  — ring attention when the active mesh (parallel.mesh
  ``set_current_mesh``/``use_mesh``) has an ``sp`` axis of size > 1,
  else the Pallas flash kernel on TPU / dense composition elsewhere.
- ``flash`` / ``dense`` / ``ring`` / ``ulysses`` — forced choice.
"""
from __future__ import annotations

from .registry import register

__all__ = []


def _is_tracer(x):
    import jax.core
    return isinstance(x, jax.core.Tracer)


def _attention(attrs, query, key, value, segment_ids=None):
    import math
    causal = bool(attrs.get("causal", False))
    scale = float(attrs.get("scale", 0.0)) or \
        1.0 / math.sqrt(query.shape[-1])
    impl = str(attrs.get("impl", "auto"))
    axis = str(attrs.get("mesh_axis", "sp"))
    from ..parallel.mesh import current_mesh, mesh_axes
    from ..parallel.flash_attention import flash_attention, _jnp_reference
    from ..parallel.ring_attention import (ring_attention,
                                           ulysses_attention)

    mesh = current_mesh()
    has_sp = mesh is not None and mesh_axes(mesh).get(axis, 1) > 1
    if impl == "auto":
        impl = "ring" if has_sp else "flash"
    if segment_ids is not None and impl in ("ring", "ulysses"):
        # packed batches: the sequence-sharded kernels do not take a
        # segment plane — block the silent wrong answer
        raise ValueError(
            "_contrib_flash_attention: segment_ids (packed batches) "
            "is supported by impl='flash'/'dense' only, not %r" % impl)
    if impl in ("ring", "ulysses"):
        if has_sp:
            # sequence-shard eager inputs onto the mesh (T over the sp
            # axis) — the shard_map computation spans the mesh's device
            # set, while op inputs arrive committed to one device
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            sh = NamedSharding(mesh, P(None, axis))
            if not _is_tracer(query):
                query, key, value = (jax.device_put(x, sh)
                                     for x in (query, key, value))
        fn = ring_attention if impl == "ring" else ulysses_attention
        return fn(query, key, value, mesh=mesh, axis=axis,
                  causal=causal, scale=scale)
    if impl == "dense":
        return _jnp_reference(query, key, value, scale, causal,
                              segment_ids=segment_ids)
    if impl == "flash":
        return flash_attention(query, key, value, causal=causal,
                               scale=scale,
                               block_q=int(attrs.get("block_q", 512)),
                               block_k=int(attrs.get("block_k", 512)),
                               segment_ids=segment_ids)
    raise ValueError("_contrib_flash_attention: unknown impl %r" % impl)


def _decode_attention(attrs, query, key_cache, value_cache, lengths):
    import math
    scale = float(attrs.get("scale", 0.0)) or \
        1.0 / math.sqrt(query.shape[-1])
    impl = str(attrs.get("impl", "auto"))
    from ..parallel.flash_attention import flash_decode, _jnp_decode
    if impl == "dense":
        return _jnp_decode(query, key_cache, value_cache, lengths, scale)
    if impl in ("auto", "flash"):
        return flash_decode(query, key_cache, value_cache, lengths,
                            scale=scale,
                            block_k=int(attrs.get("block_k", 128)),
                            force_pallas=impl == "flash")
    raise ValueError(
        "_contrib_decode_attention: unknown impl %r (auto|flash|dense)"
        % impl)


register("_contrib_decode_attention", _decode_attention,
         arg_names=("query", "key_cache", "value_cache", "lengths"),
         no_jit=True,   # dispatch (TPU kernel vs jnp) is the op's own
         defaults={"scale": 0.0, "impl": "auto", "block_k": 128},
         attr_docs={"scale": "score scale; 0 = 1/sqrt(head_dim)",
                    "impl": "auto|flash|dense (flash forces the "
                            "Pallas kernel, interpret mode off-TPU)",
                    "block_k": "decode kernel key/value block"},
         description="One autoregressive decode step of cached-KV "
                     "attention: query (B, 1, H, D) against a "
                     "gathered KV cache (B, T, H, D) with per-row "
                     "valid-key counts (B,) — positions beyond a "
                     "row's length carry exact-zero weight "
                     "(serving.kvcache's paged-gather contract).")


register("_contrib_flash_attention", _attention,
         arg_names=("query", "key", "value"),
         no_jit=True,   # shard_map placement is managed by the op body
         defaults={"causal": False, "scale": 0.0, "impl": "auto",
                   "mesh_axis": "sp", "block_q": 512, "block_k": 512},
         attr_docs={"causal": "apply a causal (lower-triangular) mask",
                    "scale": "score scale; 0 = 1/sqrt(head_dim)",
                    "impl": "auto|flash|dense|ring|ulysses",
                    "mesh_axis": "mesh axis carrying the sequence shards",
                    "block_q": "flash kernel query block",
                    "block_k": "flash kernel key/value block"},
         description="Fused attention over (B, T, H, D); an optional "
                     "4th input carries the (B, T) int32 segment-id "
                     "plane of a packed batch (bucketing.packing) — "
                     "cross-segment attention masks to exact zero "
                     "(impl flash/dense).")
