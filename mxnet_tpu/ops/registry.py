"""Operator registry — the TPU-native equivalent of the NNVM op registry.

Reference model (include/mxnet/op_attr_types.h, src/operator/*): each op
registers FCompute kernels per device plus attribute functors
(FInferShape/FInferType/FGradient/FMutateInputs...). On TPU the design
collapses dramatically:

- An op's body is ONE pure JAX function ``forward(attrs, *inputs)`` —
  XLA compiles it for any backend, so there is no per-device kernel pair
  (``X.cc``/``X.cu``) and no mshadow expression layer.
- Gradients come from ``jax.vjp`` over the traced graph — no per-op
  FGradient registration.
- Shape/type inference comes from ``jax.eval_shape`` over the same
  function — no per-op FInferShape/FInferType.

What remains per-op, and is registered here: the forward body, input arg
names (for Symbol ``list_arguments``), number of outputs, RNG needs
(counter-based like the reference's parallel-random resource), mutable
input indices (BatchNorm aux-state writeback, optimizer update ops), and
attribute parsing (the dmlc ``Parameter`` struct role).

Eager dispatch mirrors ``Imperative::Invoke``
(src/imperative/imperative.cc:87): op + static attrs → a cached
``jax.jit`` callable (the analogue of the per-signature CachedOp cache).
"""
from __future__ import annotations

import ast
import functools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

from ..base import MXNetError, Registry

__all__ = ["OpDef", "register", "get_op", "find_op", "list_ops", "invoke",
           "normalize_attrs", "attr_key"]

_OP_REGISTRY: Registry = Registry("operator")


class OpDef:
    """A registered operator.

    Parameters
    ----------
    name : canonical op name (e.g. ``FullyConnected``, ``_plus_scalar``).
    forward : ``forward(attrs: dict, *inputs, rng=None) -> array | tuple``.
        Pure JAX function. If ``mutable_inputs`` is set, the returned tuple
        carries ``num_outputs`` real outputs followed by one updated value
        per mutable input (in order).
    arg_names : names of tensor inputs (Symbol ``list_arguments`` order).
    defaults : attribute name → default value (dmlc Parameter struct role).
    num_outputs : int, or callable ``attrs -> int`` for variadic outputs.
    key_var_num_args : attr holding the variadic input count (Concat's
        ``num_args``), mirroring nnvm's ``key_var_num_args``.
    needs_rng : op consumes a PRNG key (samplers, Dropout).
    mutable_inputs : indices of inputs updated in place (FMutateInputs).
    """

    def __init__(self, name: str, forward: Callable,
                 arg_names: Sequence[str] = ("data",),
                 defaults: Optional[Dict[str, Any]] = None,
                 num_outputs: Union[int, Callable] = 1,
                 key_var_num_args: Optional[str] = None,
                 needs_rng: bool = False,
                 mutable_inputs: Sequence[int] = (),
                 arg_names_fn: Optional[Callable] = None,
                 description: str = "",
                 attr_docs: Optional[Dict[str, str]] = None,
                 attr_ranges: Optional[Dict[str, tuple]] = None,
                 no_jit: bool = False):
        self.name = name
        self.forward = forward
        self.arg_names = list(arg_names)
        self.defaults = dict(defaults or {})
        self.num_outputs = num_outputs
        self.key_var_num_args = key_var_num_args
        self.needs_rng = needs_rng
        self.mutable_inputs = tuple(mutable_inputs)
        self.arg_names_fn = arg_names_fn  # attrs -> effective input names
        # no_jit: forward manages its own compilation/placement (e.g.
        # shard_map over a multi-device mesh, which a single-device
        # eager jit wrapper would reject)
        self.no_jit = bool(no_jit)
        self.description = description or (forward.__doc__ or "")
        # the dmlc Parameter-struct tier (SURVEY §5.6 tier 2): per-attr
        # documentation and (lo, hi) ranges; both feed the generated
        # frontend stubs' docstrings, ranges also validate at invoke
        self.attr_docs = dict(attr_docs or {})
        self.attr_ranges = dict(attr_ranges or {})

    def doc_signature(self) -> str:
        """Human signature + parameter table for generated stubs (the
        role of the reference's codegen from DMLC_DECLARE_FIELD docs,
        python/mxnet/ndarray/register.py:30)."""
        lines = ["%s(%s, **attrs)" % (self.name,
                                      ", ".join(self.arg_names)), ""]
        if self.description:
            lines += [self.description.strip(), ""]
        if self.defaults:
            lines.append("Parameters")
            lines.append("----------")
            for key, default in self.defaults.items():
                if key.startswith("__"):
                    continue
                entry = "%s : default %r" % (key, default)
                if key in self.attr_ranges:
                    entry += ", range %s" % (self.attr_ranges[key],)
                lines.append(entry)
                if key in self.attr_docs:
                    lines.append("    " + self.attr_docs[key])
        return "\n".join(lines)

    def validate_attrs(self, nattrs: Dict[str, Any]) -> None:
        """Range checks from the param tier (dmlc set_range role)."""
        for key, (lo, hi) in self.attr_ranges.items():
            val = nattrs.get(key)
            if val is None or not isinstance(val, (int, float)):
                continue
            if (lo is not None and val < lo) or \
                    (hi is not None and val > hi):
                raise MXNetError(
                    "%s: attribute %s=%r outside valid range [%s, %s]"
                    % (self.name, key, val, lo, hi))

    # -- helpers ---------------------------------------------------------
    def resolve_num_outputs(self, attrs: Dict[str, Any]) -> int:
        if callable(self.num_outputs):
            return self.num_outputs(attrs)
        return self.num_outputs

    def resolve_arg_names(self, attrs: Dict[str, Any], num_inputs=None) -> List[str]:
        if self.key_var_num_args:
            n = int(attrs.get(self.key_var_num_args,
                              num_inputs if num_inputs is not None else 1))
            base = self.arg_names[0] if self.arg_names else "arg"
            return ["%s%d" % (base, i) for i in range(n)]
        if self.arg_names_fn is not None:
            return list(self.arg_names_fn(normalize_attrs(self, attrs)))
        return list(self.arg_names)

    def __repr__(self):
        return "OpDef(%s)" % self.name


def register(name: str, forward: Optional[Callable] = None, *,
             aliases: Sequence[str] = (), **kwargs) -> Union[OpDef, Callable]:
    """Register an operator; usable as function or decorator."""
    def _do(fwd):
        op = OpDef(name, fwd, **kwargs)
        _OP_REGISTRY.register(name)(op)
        for a in aliases:
            _OP_REGISTRY.register(a)(op)
        return op
    if forward is not None:
        return _do(forward)
    return _do


def get_op(name: str) -> OpDef:
    try:
        return _OP_REGISTRY.get(name)
    except KeyError:
        raise MXNetError("Operator '%s' is not registered" % name)


def find_op(name: str) -> Optional[OpDef]:
    return _OP_REGISTRY.find(name)


def list_ops() -> List[str]:
    return sorted(_OP_REGISTRY.keys())


# ---------------------------------------------------------------------------
# Attribute normalization (dmlc Parameter parsing role)
# ---------------------------------------------------------------------------

_BOOL_STR = {"true": True, "True": True, "1": True,
             "false": False, "False": False, "0": False}


def _parse_attr_value(v):
    if not isinstance(v, str):
        return v
    if v in _BOOL_STR:
        return _BOOL_STR[v]
    if v == "None":
        return None
    if v.startswith("__subgraph__:"):
        from .control_flow import Subgraph
        return Subgraph.from_json_attr(v)
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def normalize_attrs(op: OpDef, attrs: Dict[str, Any]) -> Dict[str, Any]:
    """Merge with defaults, parse stringly-typed values (from Symbol
    JSON or frontend kwargs), and range-check — mirroring dmlc
    Parameter::Init + set_range."""
    out = dict(op.defaults)
    for k, v in attrs.items():
        if v is None and k in out:
            continue
        out[k] = _parse_attr_value(v)
    if op.attr_ranges:
        op.validate_attrs(out)
    return out


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def attr_key(attrs: Dict[str, Any]):
    return tuple(sorted((k, _hashable(v)) for k, v in attrs.items()))


# ---------------------------------------------------------------------------
# Eager dispatch with jit cache (Imperative::Invoke analogue)
# ---------------------------------------------------------------------------

_jit_cache: Dict[Tuple, Callable] = {}
_jit_lock = threading.Lock()


def _get_jitted(op: OpDef, nattrs: Dict[str, Any], n_inputs: int):
    key = (op.name, attr_key(nattrs), n_inputs, op.needs_rng)
    fn = _jit_cache.get(key)
    if fn is None:
        from .. import compile_watch
        arg_names = list(op.arg_names) if op.arg_names else None
        if op.needs_rng:
            def raw(rng, *arrays):
                return op.forward(nattrs, *arrays, rng=rng)
            names = ["rng"] + (arg_names or [])
        else:
            def raw(*arrays):
                return op.forward(nattrs, *arrays)
            names = arg_names

        def describe(*arrays):
            return compile_watch.describe_arrays(names, arrays)

        # program identity includes the op's static attrs (a _zeros
        # per param shape is specialization, not churn). Plain eager
        # micro-ops are polymorphic by design, so only CachedOp graphs
        # — one hybridized program, site "op:_cachedopN.<head>" —
        # participate in recompile-storm detection.
        is_cached = op.name.startswith("_cachedop")
        token = getattr(op, "cache_token", None)
        cache_site = None
        if is_cached and token is not None:
            # the display site's instance counter is process-local;
            # on disk the program is (head, graph hash, attrs, sig) —
            # so a rebuilt identical block hits, and creation order
            # can never map an entry to the wrong graph
            cache_site = "op:_cachedop.%s" % op.name.split(".", 1)[-1]
        fn = compile_watch.jit(raw, "op:%s" % op.name,
                               describe=describe, statics=key[1:],
                               storm=is_cached,
                               cache=token is not None or not is_cached,
                               cache_token=token,
                               cache_site=cache_site)
        with _jit_lock:
            _jit_cache[key] = fn
    return fn


def _align_device_sets(input_arrays):
    """MXNet semantics let one op mix arrays the user placed on
    different devices; jax refuses eager math across device sets. When
    inputs disagree, re-place the minority onto the widest device set
    (replicated if it is a mesh) — the analogue of the implicit copies
    the reference's cross-device-copy op inserted."""
    if len(input_arrays) < 2:
        return input_arrays
    shardings = [getattr(a, "sharding", None) for a in input_arrays]
    first = next((s for s in shardings if s is not None), None)
    if first is None or all(s is None or s == first for s in shardings):
        return input_arrays  # common case: everything already agrees
    import jax
    sets = {}
    for s in shardings:
        if s is not None:
            sets.setdefault(tuple(sorted(d.id for d in s.device_set)), s)
    if len(sets) <= 1:
        return input_arrays
    widest = max(sets.values(), key=lambda s: len(s.device_set))
    try:
        from jax.sharding import NamedSharding, PartitionSpec as P
        target = NamedSharding(widest.mesh, P()) \
            if isinstance(widest, NamedSharding) else widest
    except Exception:
        target = widest
    out = []
    for a in input_arrays:
        s = getattr(a, "sharding", None)
        if s is not None and s.device_set != widest.device_set:
            a = jax.device_put(a, target)
        out.append(a)
    return out


def invoke(op: OpDef, input_arrays: Sequence[Any], attrs: Dict[str, Any],
           rng=None):
    """Eagerly execute ``op`` on raw jax arrays; returns tuple
    ``(outputs, aux_updates)`` where aux_updates is a list of (input_index,
    new_value) for mutable inputs."""
    input_arrays = _align_device_sets(list(input_arrays))
    nattrs = normalize_attrs(op, attrs)
    if op.no_jit:
        fn = (lambda *a: op.forward(nattrs, *a)) if not op.needs_rng \
            else (lambda rng_, *a: op.forward(nattrs, *a, rng=rng_))
    else:
        fn = _get_jitted(op, nattrs, len(input_arrays))
    if op.needs_rng:
        if rng is None:
            from .. import random as _random
            rng = _random.new_key()
        result = fn(rng, *input_arrays)
    else:
        result = fn(*input_arrays)
    if not isinstance(result, (tuple, list)):
        result = (result,)
    n_out = op.resolve_num_outputs(nattrs)
    outputs = tuple(result[:n_out])
    aux_updates = []
    if op.mutable_inputs:
        extras = result[n_out:]
        for idx, val in zip(op.mutable_inputs, extras):
            aux_updates.append((idx, val))
    return outputs, aux_updates
