"""Indexing / gather / ordering operators.

Reference: src/operator/tensor/indexing_op.{h,cc} (take, batch_take,
one_hot, gather_nd, scatter_nd, Embedding), ordering_op.cc (topk, sort,
argsort). XLA lowers gathers/scatters natively; no hand-written kernels
needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

_D = ("data",)


def _take(attrs, a, indices):
    axis = int(attrs.get("axis", 0))
    mode = attrs.get("mode", "clip")
    idx = indices.astype(jnp.int32)
    n = a.shape[axis]
    if mode == "wrap":
        idx = jnp.mod(idx, n)
    else:  # clip
        idx = jnp.clip(idx, 0, n - 1)
    return jnp.take(a, idx, axis=axis)


register("take", _take, arg_names=("a", "indices"),
         defaults={"axis": 0, "mode": "clip"})


def _batch_take(attrs, a, indices):
    idx = jnp.clip(indices.astype(jnp.int32), 0, a.shape[1] - 1)
    return jnp.take_along_axis(a, idx.reshape(-1, 1), axis=1).reshape(idx.shape)


register("batch_take", _batch_take, arg_names=("a", "indices"))


def _one_hot(attrs, indices):
    depth = int(attrs["depth"])
    on = float(attrs.get("on_value", 1.0))
    off = float(attrs.get("off_value", 0.0))
    dtype = jnp.dtype(attrs.get("dtype", "float32"))
    idx = indices.astype(jnp.int32)
    eye = jax.nn.one_hot(idx, depth, dtype=dtype)
    return eye * jnp.asarray(on - off, dtype) + jnp.asarray(off, dtype)


register("one_hot", _one_hot, arg_names=("indices",),
         defaults={"depth": 1, "on_value": 1.0, "off_value": 0.0,
                   "dtype": "float32"})


def _embedding(attrs, data, weight):
    idx = data.astype(jnp.int32)
    idx = jnp.clip(idx, 0, weight.shape[0] - 1)
    return jnp.take(weight, idx, axis=0)


register("Embedding", _embedding, arg_names=("data", "weight"),
         defaults={"input_dim": 0, "output_dim": 0, "dtype": "float32",
                   "sparse_grad": False},
         attr_docs={"input_dim": "vocabulary size",
                    "output_dim": "embedding width",
                    "sparse_grad": "produce a row_sparse gradient"},
         attr_ranges={"input_dim": (0, None), "output_dim": (0, None)})


def _gather_nd(attrs, data, indices):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


register("gather_nd", _gather_nd, arg_names=("data", "indices"))


def _scatter_nd(attrs, data, indices):
    shape = tuple(attrs["shape"])
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


register("scatter_nd", _scatter_nd, arg_names=("data", "indices"),
         defaults={"shape": ()})


def _pick(attrs, data, index):
    axis = attrs.get("axis", -1)
    axis = data.ndim - 1 if axis is None else int(axis)
    keepdims = bool(attrs.get("keepdims", False))
    mode = attrs.get("mode", "clip")
    idx = index.astype(jnp.int32)
    n = data.shape[axis]
    idx = jnp.mod(idx, n) if mode == "wrap" else jnp.clip(idx, 0, n - 1)
    idxe = jnp.expand_dims(idx, axis % data.ndim)
    out = jnp.take_along_axis(data, idxe, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis % data.ndim)
    return out


register("pick", _pick, arg_names=("data", "index"),
         defaults={"axis": -1, "keepdims": False, "mode": "clip"},
         aliases=("choose_element_0index",))


# ---------------------------------------------------------------------------
# ordering
# ---------------------------------------------------------------------------

def _sort(attrs, x):
    axis = attrs.get("axis", -1)
    is_ascend = bool(attrs.get("is_ascend", True))
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    out = jnp.sort(x, axis=int(axis))
    if not is_ascend:
        out = jnp.flip(out, axis=int(axis))
    return out


register("sort", _sort, arg_names=_D, defaults={"axis": -1, "is_ascend": True})


def _argsort(attrs, x):
    axis = attrs.get("axis", -1)
    is_ascend = bool(attrs.get("is_ascend", True))
    dtype = jnp.dtype(attrs.get("dtype", "float32"))
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    idx = jnp.argsort(x, axis=int(axis))
    if not is_ascend:
        idx = jnp.flip(idx, axis=int(axis))
    return idx.astype(dtype)


register("argsort", _argsort, arg_names=_D,
         defaults={"axis": -1, "is_ascend": True, "dtype": "float32"})


def _topk_outputs(attrs):
    ret_typ = attrs.get("ret_typ", "indices")
    return 2 if ret_typ == "both" else 1


def _topk(attrs, x):
    axis = attrs.get("axis", -1)
    k = int(attrs.get("k", 1))
    ret_typ = attrs.get("ret_typ", "indices")
    is_ascend = bool(attrs.get("is_ascend", False))
    dtype = jnp.dtype(attrs.get("dtype", "float32"))
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    axis = int(axis) % x.ndim
    xs = jnp.moveaxis(x, axis, -1)
    neg = xs if is_ascend else -xs
    # lax.top_k returns largest; negate for ascending
    vals, idx = jax.lax.top_k(-neg, k)
    vals = vals if is_ascend else -(-vals)  # placeholder symmetry
    sel_vals = jnp.take_along_axis(xs, idx, axis=-1)
    sel_vals = jnp.moveaxis(sel_vals, -1, axis)
    idx_o = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "value":
        return sel_vals
    if ret_typ == "indices":
        return idx_o.astype(dtype)
    if ret_typ == "mask":
        mask = jnp.zeros(xs.shape, dtype=x.dtype)
        mask = mask.at[..., 0].set(0)  # shape anchor
        onehots = jax.nn.one_hot(idx, xs.shape[-1], dtype=x.dtype).sum(-2)
        return jnp.moveaxis(onehots, -1, axis)
    # both
    return sel_vals, idx_o.astype(dtype)


register("topk", _topk, arg_names=_D,
         defaults={"axis": -1, "k": 1, "ret_typ": "indices",
                   "is_ascend": False, "dtype": "float32"},
         num_outputs=_topk_outputs)


def _boolean_mask(attrs, data, index):
    # Dynamic-shape op: XLA needs static shapes, so we return data rows
    # where mask!=0 compacted to the front and zero-padded (documented
    # divergence); host fallback in NDArray layer gives exact semantics.
    axis = int(attrs.get("axis", 0))
    mask = (index != 0)
    order = jnp.argsort(~mask, stable=True)
    return jnp.take(data, order, axis=axis) * jnp.expand_dims(
        jnp.sort(mask)[::-1], tuple(range(1, data.ndim))).astype(data.dtype)


register("_contrib_boolean_mask", _boolean_mask, arg_names=("data", "index"),
         defaults={"axis": 0})


def _index_copy(attrs, old, idx, new):
    return old.at[idx.astype(jnp.int32)].set(new)


register("_contrib_index_copy", _index_copy,
         arg_names=("old_tensor", "index_vector", "new_tensor"))


# ---------------------------------------------------------------------------
# __getitem__ as a first-class recorded op
# ---------------------------------------------------------------------------
# The reference routes NDArray indexing through op.slice / op.take /
# op.gather_nd so gradients flow (ref: python/mxnet/ndarray/ndarray.py:507-796
# _get_nd_basic_indexing / _get_nd_advanced_indexing). We do the same with a
# single generic op: the structural part of the index key (slices, ints,
# None, Ellipsis) is a hashable attr `spec`, and any array indices become
# tensor *inputs* — so the whole lookup is one XLA gather on the tape, with
# its scatter-add VJP supplied by jax.

def _getitem_impl(attrs, data, *index_arrays):
    it = iter(index_arrays)
    idx = []
    for item in attrs["spec"]:
        kind = item[0]
        if kind == "s":           # slice
            idx.append(slice(item[1], item[2], item[3]))
        elif kind == "i":         # integer (legacy saved graphs)
            idx.append(item[1])
        elif kind == "b":         # bool scalar: 0-d mask, static shape
            idx.append(item[1])
        elif kind == "n":         # newaxis
            idx.append(None)
        elif kind == "e":         # ellipsis
            idx.append(Ellipsis)
        else:                     # "a": array index (advanced indexing)
            idx.append(next(it).astype(jnp.int32))
    return data[tuple(idx)]


register("_getitem", _getitem_impl, arg_names=("data",),
         defaults={"spec": (), "num_arrays": 0},
         key_var_num_args="num_arrays")


def _sparse_retain_op(attrs, data, indices):
    """Dense lowering of row retention (ref
    src/operator/tensor/sparse_retain.cc): rows of ``data`` whose index
    is absent from ``indices`` become zero — on row_sparse storage the
    ndarray.sparse.retain wrapper drops them instead, same contract."""
    import jax.numpy as jnp
    rows = jnp.arange(data.shape[0])
    keep = jnp.isin(rows, indices.astype(jnp.int32))
    return data * keep.astype(data.dtype).reshape(
        (-1,) + (1,) * (data.ndim - 1))


register("_sparse_retain", _sparse_retain_op,
         arg_names=("data", "indices"))
