"""Random sampling operators.

Reference: src/operator/random/sample_op.cc (_random_*), multisample_op.cc
(_sample_* tensor-parameter variants), sample_multinomial_op.cc, shuffle.
All draw from the framework's counter-based PRNG chain (mxnet_tpu.random)
— the TPU-native replacement for the reference's per-device random
resource (src/resource.cc kParallelRandom).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _shape_dtype(attrs):
    shape = attrs.get("shape", ())
    if shape is None:
        shape = ()
    if isinstance(shape, int):
        shape = (shape,)
    return tuple(shape), jnp.dtype(attrs.get("dtype") or "float32")


def _random_uniform(attrs, rng=None):
    shape, dt = _shape_dtype(attrs)
    lo = float(attrs.get("low", 0.0))
    hi = float(attrs.get("high", 1.0))
    return jax.random.uniform(rng, shape, dtype=dt, minval=lo, maxval=hi)


register("_random_uniform", _random_uniform, arg_names=(), needs_rng=True,
         defaults={"low": 0.0, "high": 1.0, "shape": (), "dtype": "float32",
                   "ctx": None})


def _random_normal(attrs, rng=None):
    shape, dt = _shape_dtype(attrs)
    loc = float(attrs.get("loc", 0.0))
    scale = float(attrs.get("scale", 1.0))
    return loc + scale * jax.random.normal(rng, shape, dtype=dt)


register("_random_normal", _random_normal, arg_names=(), needs_rng=True,
         defaults={"loc": 0.0, "scale": 1.0, "shape": (), "dtype": "float32",
                   "ctx": None})


def _random_gamma(attrs, rng=None):
    shape, dt = _shape_dtype(attrs)
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    return jax.random.gamma(rng, alpha, shape, dtype=dt) * beta


register("_random_gamma", _random_gamma, arg_names=(), needs_rng=True,
         defaults={"alpha": 1.0, "beta": 1.0, "shape": (), "dtype": "float32",
                   "ctx": None})


def _random_exponential(attrs, rng=None):
    shape, dt = _shape_dtype(attrs)
    lam = float(attrs.get("lam", 1.0))
    return jax.random.exponential(rng, shape, dtype=dt) / lam


register("_random_exponential", _random_exponential, arg_names=(),
         needs_rng=True,
         defaults={"lam": 1.0, "shape": (), "dtype": "float32", "ctx": None})


def _random_poisson(attrs, rng=None):
    shape, dt = _shape_dtype(attrs)
    lam = float(attrs.get("lam", 1.0))
    return jax.random.poisson(rng, lam, shape).astype(dt)


register("_random_poisson", _random_poisson, arg_names=(), needs_rng=True,
         defaults={"lam": 1.0, "shape": (), "dtype": "float32", "ctx": None})


def _random_randint(attrs, rng=None):
    shape, _ = _shape_dtype(attrs)
    dt = jnp.dtype(attrs.get("dtype") or "int32")
    lo = int(attrs.get("low", 0))
    hi = int(attrs.get("high", 1))
    return jax.random.randint(rng, shape, lo, hi).astype(dt)


register("_random_randint", _random_randint, arg_names=(), needs_rng=True,
         defaults={"low": 0, "high": 1, "shape": (), "dtype": "int32",
                   "ctx": None})


def _random_negative_binomial(attrs, rng=None):
    shape, dt = _shape_dtype(attrs)
    k = float(attrs.get("k", 1))
    p = float(attrs.get("p", 1.0))
    # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
    g = jax.random.gamma(rng, k, shape) * ((1.0 - p) / p)
    return jax.random.poisson(jax.random.fold_in(rng, 1), g, shape).astype(dt)


register("_random_negative_binomial", _random_negative_binomial,
         arg_names=(), needs_rng=True,
         defaults={"k": 1, "p": 1.0, "shape": (), "dtype": "float32",
                   "ctx": None})


def _random_generalized_negative_binomial(attrs, rng=None):
    shape, dt = _shape_dtype(attrs)
    mu = float(attrs.get("mu", 1.0))
    alpha = float(attrs.get("alpha", 1.0))
    if alpha == 0.0:
        return jax.random.poisson(rng, mu, shape).astype(dt)
    k = 1.0 / alpha
    p = k / (k + mu)
    g = jax.random.gamma(rng, k, shape) * ((1.0 - p) / p)
    return jax.random.poisson(jax.random.fold_in(rng, 1), g, shape).astype(dt)


register("_random_generalized_negative_binomial",
         _random_generalized_negative_binomial, arg_names=(), needs_rng=True,
         defaults={"mu": 1.0, "alpha": 1.0, "shape": (), "dtype": "float32",
                   "ctx": None})


# ---- tensor-parameter samplers (_sample_*) --------------------------------

def _bshape(param, extra):
    extra = tuple(extra) if extra else ()
    return tuple(param.shape) + extra


def _sample_uniform(attrs, low, high, rng=None):
    shape = _bshape(low, attrs.get("shape", ()))
    dt = jnp.dtype(attrs.get("dtype") or "float32")
    u = jax.random.uniform(rng, shape, dtype=dt)
    nd_extra = len(shape) - low.ndim
    lo = low.reshape(low.shape + (1,) * nd_extra)
    hi = high.reshape(high.shape + (1,) * nd_extra)
    return lo + u * (hi - lo)


register("_sample_uniform", _sample_uniform, arg_names=("low", "high"),
         needs_rng=True, defaults={"shape": (), "dtype": "float32"})


def _sample_normal(attrs, mu, sigma, rng=None):
    shape = _bshape(mu, attrs.get("shape", ()))
    dt = jnp.dtype(attrs.get("dtype") or "float32")
    z = jax.random.normal(rng, shape, dtype=dt)
    nd_extra = len(shape) - mu.ndim
    m = mu.reshape(mu.shape + (1,) * nd_extra)
    s = sigma.reshape(sigma.shape + (1,) * nd_extra)
    return m + z * s


register("_sample_normal", _sample_normal, arg_names=("mu", "sigma"),
         needs_rng=True, defaults={"shape": (), "dtype": "float32"})


def _sample_gamma(attrs, alpha, beta, rng=None):
    shape = _bshape(alpha, attrs.get("shape", ()))
    dt = jnp.dtype(attrs.get("dtype") or "float32")
    nd_extra = len(shape) - alpha.ndim
    a = alpha.reshape(alpha.shape + (1,) * nd_extra)
    b = beta.reshape(beta.shape + (1,) * nd_extra)
    g = jax.random.gamma(rng, jnp.broadcast_to(a, shape).astype(dt), shape)
    return g * b


register("_sample_gamma", _sample_gamma, arg_names=("alpha", "beta"),
         needs_rng=True, defaults={"shape": (), "dtype": "float32"})


def _sample_multinomial(attrs, data, rng=None):
    shape = attrs.get("shape", ())
    if shape is None:
        shape = ()
    if isinstance(shape, int):
        shape = (shape,)
    n = 1
    for s in shape:
        n *= s
    n = max(n, 1)
    get_prob = bool(attrs.get("get_prob", False))
    dt = jnp.dtype(attrs.get("dtype") or "int32")
    logits = jnp.log(jnp.clip(data, 1e-20, None))
    if data.ndim == 1:
        draws = jax.random.categorical(rng, logits, shape=(n,))
        out = draws.reshape(shape).astype(dt) if shape else draws[0].astype(dt)
    else:
        draws = jax.random.categorical(rng, logits[:, None, :], axis=-1,
                                       shape=(data.shape[0], n))
        out = draws.reshape((data.shape[0],) + tuple(shape)).astype(dt)
    if get_prob:
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits),
            out.reshape(data.shape[0], -1).astype(jnp.int32)
            if data.ndim > 1 else out.reshape(-1).astype(jnp.int32)[None],
            axis=-1)
        lp = lp.reshape(out.shape).astype(jnp.float32)
        return out, lp
    return out


register("_sample_multinomial", _sample_multinomial, arg_names=("data",),
         needs_rng=True,
         defaults={"shape": (), "get_prob": False, "dtype": "int32"},
         num_outputs=lambda attrs: 2 if attrs.get("get_prob", False) else 1,
         aliases=("multinomial",))


def _shuffle(attrs, data, rng=None):
    return jax.random.permutation(rng, data, axis=0)


register("_shuffle", _shuffle, arg_names=("data",), needs_rng=True,
         aliases=("shuffle",))
