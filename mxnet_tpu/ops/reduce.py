"""Reduction and broadcast-shape operators.

Reference: src/operator/tensor/broadcast_reduce_op_value.cc,
broadcast_reduce_op_index.cc (argmax/argmin), L2 norm in
broadcast_reduce_op.h. Attribute semantics preserved: ``axis`` may be
None/int/tuple, ``exclude=True`` reduces over the complement, ``keepdims``
keeps reduced dims as 1.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register

_D = ("data",)


def _norm_axis(attrs, ndim):
    axis = attrs.get("axis", None)
    if axis is None or axis == () or axis == []:
        axes = tuple(range(ndim))
    elif isinstance(axis, int):
        axes = (axis % ndim,)
    else:
        axes = tuple(a % ndim for a in axis)
    if attrs.get("exclude", False):
        axes = tuple(a for a in range(ndim) if a not in axes)
    return axes


def _reg_reduce(name, fn, aliases=()):
    def fwd(attrs, x, _f=fn):
        axes = _norm_axis(attrs, x.ndim)
        return _f(x, axes, bool(attrs.get("keepdims", False)))
    register(name, fwd, arg_names=_D,
             defaults={"axis": None, "keepdims": False, "exclude": False},
             aliases=aliases)


_reg_reduce("sum", lambda x, a, k: jnp.sum(x, axis=a, keepdims=k),
            aliases=("sum_axis",))
_reg_reduce("mean", lambda x, a, k: jnp.mean(x, axis=a, keepdims=k))
_reg_reduce("prod", lambda x, a, k: jnp.prod(x, axis=a, keepdims=k))
_reg_reduce("nansum", lambda x, a, k: jnp.nansum(x, axis=a, keepdims=k))
_reg_reduce("nanprod", lambda x, a, k: jnp.nanprod(x, axis=a, keepdims=k))
_reg_reduce("max", lambda x, a, k: jnp.max(x, axis=a, keepdims=k),
            aliases=("max_axis",))
_reg_reduce("min", lambda x, a, k: jnp.min(x, axis=a, keepdims=k),
            aliases=("min_axis",))


def _norm(attrs, x):
    axes = _norm_axis(attrs, x.ndim)
    ord_ = int(attrs.get("ord", 2))
    k = bool(attrs.get("keepdims", False))
    if ord_ == 1:
        return jnp.sum(jnp.abs(x), axis=axes, keepdims=k)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=k))


register("norm", _norm, arg_names=_D,
         defaults={"axis": None, "keepdims": False, "exclude": False, "ord": 2})


def _reg_argminmax(name, fn):
    def fwd(attrs, x, _f=fn):
        axis = attrs.get("axis", None)
        k = bool(attrs.get("keepdims", False))
        # MXNet returns float dtype indices (same dtype family as input)
        if axis is None:
            r = _f(x.reshape(-1), axis=0)
            return r.astype(jnp.float32)
        r = _f(x, axis=int(axis))
        if k:
            r = jnp.expand_dims(r, int(axis))
        return r.astype(jnp.float32)
    register(name, fwd, arg_names=_D, defaults={"axis": None, "keepdims": False})


_reg_argminmax("argmax", jnp.argmax)
_reg_argminmax("argmin", jnp.argmin)

register("argmax_channel",
         lambda attrs, x: jnp.argmax(x, axis=1).astype(jnp.float32),
         arg_names=_D)


# ---------------------------------------------------------------------------
# Broadcast shape manipulation
# ---------------------------------------------------------------------------

def _broadcast_to(attrs, x):
    shape = tuple(attrs["shape"])
    # 0 in target shape means "keep input dim" (MXNet convention)
    shape = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


register("broadcast_to", _broadcast_to, arg_names=_D, defaults={"shape": ()})


def _broadcast_axis(attrs, x):
    axis = attrs.get("axis", ())
    size = attrs.get("size", ())
    if isinstance(axis, int):
        axis = (axis,)
    if isinstance(size, int):
        size = (size,)
    shape = list(x.shape)
    for a, s in zip(axis, size):
        shape[a] = s
    return jnp.broadcast_to(x, tuple(shape))


register("broadcast_axis", _broadcast_axis, arg_names=_D,
         defaults={"axis": (), "size": ()}, aliases=("broadcast_axes",))

register("broadcast_like",
         lambda attrs, x, y: jnp.broadcast_to(x, y.shape),
         arg_names=("lhs", "rhs"))
