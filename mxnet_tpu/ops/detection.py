"""Detection operators (reference: src/operator/contrib/ —
multibox_prior.cc, multibox_target.cc, multibox_detection.cc,
bounding_box.cc (_contrib_box_nms/_contrib_box_iou/
_contrib_bipartite_matching), roi_align.cc, and the legacy
ROIPooling (src/operator/roi_pooling.cc).

TPU-native design: everything is static-shape dense math — NMS is the
O(k²) suppression-matrix form over the top-k scored boxes (no
data-dependent loops), ROIAlign/ROIPooling gather fixed sampling grids
— so all of it jits into the surrounding program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


# ---------------------------------------------------------------------------
# box geometry helpers
# ---------------------------------------------------------------------------

def _iou_corner(a, b):
    """Pairwise IoU of corner-format boxes a (..., Na, 4) x b (..., Nb, 4)."""
    ax1, ay1, ax2, ay2 = [a[..., :, None, i] for i in range(4)]
    bx1, by1, bx2, by2 = [b[..., None, :, i] for i in range(4)]
    iw = jnp.clip(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0, None)
    ih = jnp.clip(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0, None)
    inter = iw * ih
    area_a = jnp.clip(ax2 - ax1, 0, None) * jnp.clip(ay2 - ay1, 0, None)
    area_b = jnp.clip(bx2 - bx1, 0, None) * jnp.clip(by2 - by1, 0, None)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


def _center_to_corner(boxes):
    cx, cy, w, h = [boxes[..., i] for i in range(4)]
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


# ---------------------------------------------------------------------------
# MultiBox family (SSD)
# ---------------------------------------------------------------------------

def _multibox_prior(attrs, data):
    """Anchor boxes per feature-map cell (reference:
    multibox_prior.cc). Output (1, H*W*num_anchors, 4) corner format."""
    sizes = [float(s) for s in attrs.get("sizes", (1.0,))]
    ratios = [float(r) for r in attrs.get("ratios", (1.0,))]
    steps = attrs.get("steps", (-1.0, -1.0))
    offsets = attrs.get("offsets", (0.5, 0.5))
    H, W = data.shape[2], data.shape[3]
    step_y = float(steps[0]) if steps and float(steps[0]) > 0 else 1.0 / H
    step_x = float(steps[1]) if steps and float(steps[1]) > 0 else 1.0 / W
    cy = (jnp.arange(H) + float(offsets[0])) * step_y
    cx = (jnp.arange(W) + float(offsets[1])) * step_x
    # reference ordering (multibox_prior.cc): every size with ratio[0]
    # first, then size[0] with the remaining ratios
    shapes = []
    for s in sizes:
        r = ratios[0]
        shapes.append((s * np.sqrt(r), s / np.sqrt(r)))
    for r in ratios[1:]:
        s = sizes[0]
        shapes.append((s * np.sqrt(r), s / np.sqrt(r)))
    ws = jnp.asarray([w for w, _ in shapes])
    hs = jnp.asarray([h for _, h in shapes])
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")      # (H, W)
    cxg = cxg[..., None]
    cyg = cyg[..., None]
    x1 = cxg - ws / 2
    y1 = cyg - hs / 2
    x2 = cxg + ws / 2
    y2 = cyg + hs / 2
    out = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(1, -1, 4)
    if bool(attrs.get("clip", False)):
        out = jnp.clip(out, 0.0, 1.0)
    return out.astype(jnp.float32)


register("_contrib_MultiBoxPrior", _multibox_prior, arg_names=("data",),
         defaults={"sizes": (1.0,), "ratios": (1.0,), "clip": False,
                   "steps": (-1.0, -1.0), "offsets": (0.5, 0.5)},
         aliases=("MultiBoxPrior",))


def _multibox_target(attrs, anchor, label, cls_pred):
    """Assign ground-truth to anchors (reference: multibox_target.cc).

    anchor (1, N, 4) corners; label (B, M, 5) [cls, x1, y1, x2, y2]
    with cls = -1 padding; cls_pred (B, C+1, N) (unused for matching,
    shape source only). Returns (loc_target (B, N*4),
    loc_mask (B, N*4), cls_target (B, N))."""
    overlap_thr = float(attrs.get("overlap_threshold", 0.5))
    variances = [float(v) for v in attrs.get("variances",
                                             (0.1, 0.1, 0.2, 0.2))]
    B = label.shape[0]
    N = anchor.shape[1]
    anchors = anchor[0]                                  # (N, 4)

    def per_sample(lab):
        gt_valid = lab[:, 0] >= 0                        # (M,)
        gt_boxes = lab[:, 1:5]
        iou = _iou_corner(anchors, gt_boxes)             # (N, M)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)                # (N,)
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou >= overlap_thr
        # force-match the best anchor of every valid gt. Padding rows
        # (cls = -1) scatter into a dummy slot N so they can never
        # clobber a real gt's forced match.
        best_anchor = jnp.argmax(iou, axis=0)            # (M,)
        slot = jnp.where(gt_valid, best_anchor, N)
        forced = jnp.zeros((N + 1,), bool).at[slot].set(True)[:N]
        gt_for_forced = jnp.zeros((N + 1,), jnp.int32).at[slot].set(
            jnp.arange(lab.shape[0], dtype=jnp.int32))[:N]
        matched = matched | forced
        assigned = jnp.where(forced, gt_for_forced,
                             best_gt.astype(jnp.int32))
        cls_t = jnp.where(
            matched, lab[assigned, 0].astype(jnp.int32) + 1, 0)
        # location targets: encode matched gt vs anchor (center form)
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        g = gt_boxes[assigned]
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-12)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-12)
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        tx = (gcx - acx) / jnp.maximum(aw, 1e-12) / variances[0]
        ty = (gcy - acy) / jnp.maximum(ah, 1e-12) / variances[1]
        tw = jnp.log(gw / jnp.maximum(aw, 1e-12)) / variances[2]
        th = jnp.log(gh / jnp.maximum(ah, 1e-12)) / variances[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=-1)     # (N, 4)
        mask = matched[:, None].astype(loc_t.dtype)
        return (loc_t * mask).reshape(-1), \
            jnp.broadcast_to(mask, (N, 4)).reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(per_sample)(label)
    return loc_t, loc_m, cls_t.astype(cls_pred.dtype)


register("_contrib_MultiBoxTarget", _multibox_target,
         arg_names=("anchor", "label", "cls_pred"),
         defaults={"overlap_threshold": 0.5, "ignore_label": -1.0,
                   "negative_mining_ratio": -1.0,
                   "negative_mining_thresh": 0.5,
                   "minimum_negative_samples": 0,
                   "variances": (0.1, 0.1, 0.2, 0.2)},
         num_outputs=3, aliases=("MultiBoxTarget",))


def _decode_boxes(anchors, loc_pred, variances):
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    p = loc_pred.reshape(-1, 4)
    cx = p[:, 0] * variances[0] * aw + acx
    cy = p[:, 1] * variances[1] * ah + acy
    w = jnp.exp(p[:, 2] * variances[2]) * aw
    h = jnp.exp(p[:, 3] * variances[3]) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def _nms_mask(boxes, scores, thresh, cls_id=None):
    """Keep-mask of greedy NMS as a static suppression chain: box i is
    kept iff no higher-scored KEPT box overlaps it above thresh. The
    O(k²) masked form of the reference's sorted scan. With ``cls_id``
    given, suppression only happens within a class (the reference's
    force_suppress=False semantics)."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    sb = boxes[order]
    iou = _iou_corner(sb, sb)
    overlapping = iou > thresh
    if cls_id is not None:
        sc = cls_id[order]
        overlapping = overlapping & (sc[:, None] == sc[None, :])
    above = jnp.triu(overlapping, k=1)           # [i, j]: i<j overlaps j

    def body(keep, i):
        sup = jnp.any(above[:, i] & keep & (jnp.arange(n) < i))
        keep = keep.at[i].set(~sup)
        return keep, None

    keep, _ = jax.lax.scan(body, jnp.ones((n,), bool), jnp.arange(n))
    inv = jnp.zeros((n,), jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    return keep[inv]


def _multibox_detection(attrs, cls_prob, loc_pred, anchor):
    """Decode + per-class NMS (reference: multibox_detection.cc).
    Returns (B, N, 6) rows [cls_id, score, x1, y1, x2, y2], cls_id -1
    for suppressed/background entries."""
    nms_thr = float(attrs.get("nms_threshold", 0.5))
    score_thr = float(attrs.get("threshold", 0.01))
    variances = [float(v) for v in attrs.get("variances",
                                             (0.1, 0.1, 0.2, 0.2))]
    clip = bool(attrs.get("clip", True))
    force = bool(attrs.get("force_suppress", False))
    anchors = anchor[0]

    def per_sample(probs, locs):
        boxes = _decode_boxes(anchors, locs, variances)     # (N, 4)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        cls_id = jnp.argmax(probs[1:, :], axis=0)           # skip bg
        score = jnp.max(probs[1:, :], axis=0)
        keep = _nms_mask(boxes, score, nms_thr,
                         cls_id=None if force else cls_id)
        keep = keep & (score > score_thr)
        out_id = jnp.where(keep, cls_id.astype(jnp.float32), -1.0)
        return jnp.concatenate(
            [out_id[:, None], score[:, None], boxes], axis=-1)

    return jax.vmap(per_sample)(cls_prob, loc_pred)


register("_contrib_MultiBoxDetection", _multibox_detection,
         arg_names=("cls_prob", "loc_pred", "anchor"),
         defaults={"clip": True, "threshold": 0.01, "background_id": 0,
                   "nms_threshold": 0.5, "force_suppress": False,
                   "variances": (0.1, 0.1, 0.2, 0.2), "nms_topk": -1},
         aliases=("MultiBoxDetection",))


# ---------------------------------------------------------------------------
# bounding_box.cc ops
# ---------------------------------------------------------------------------

def _box_iou(attrs, lhs, rhs):
    fmt = attrs.get("format", "corner")
    if fmt == "center":
        lhs = _center_to_corner(lhs)
        rhs = _center_to_corner(rhs)
    return _iou_corner(lhs, rhs)


register("_contrib_box_iou", _box_iou, arg_names=("lhs", "rhs"),
         defaults={"format": "corner"})


def _box_nms(attrs, data):
    """Greedy NMS over (..., N, K>=5) records
    (reference: bounding_box.cc BoxNMS). Suppressed rows get score -1;
    output keeps input order (id_index semantics simplified)."""
    thr = float(attrs.get("overlap_thresh", 0.5))
    score_thr = float(attrs.get("valid_thresh", 0.0))
    score_index = int(attrs.get("score_index", 1))
    coord_start = int(attrs.get("coord_start", 2))
    id_index = int(attrs.get("id_index", -1))
    force = bool(attrs.get("force_suppress", False))
    fmt = attrs.get("in_format", "corner"), attrs.get("out_format",
                                                      "corner")

    flat = data.reshape((-1,) + data.shape[-2:])

    def per_batch(rows):
        boxes = rows[:, coord_start:coord_start + 4]
        if fmt[0] == "center":
            boxes = _center_to_corner(boxes)
        scores = rows[:, score_index]
        ids = rows[:, id_index] if (id_index >= 0 and not force) else None
        keep = _nms_mask(boxes, scores, thr, cls_id=ids) \
            & (scores >= score_thr)
        return rows.at[:, score_index].set(
            jnp.where(keep, scores, -1.0))

    out = jax.vmap(per_batch)(flat)
    return out.reshape(data.shape)


register("_contrib_box_nms", _box_nms, arg_names=("data",),
         defaults={"overlap_thresh": 0.5, "valid_thresh": 0.0,
                   "topk": -1, "coord_start": 2, "score_index": 1,
                   "id_index": -1, "background_id": -1,
                   "force_suppress": False, "in_format": "corner",
                   "out_format": "corner"},
         aliases=("_contrib_box_non_maximum_suppression",))


def _bipartite_matching(attrs, data):
    """Greedy bipartite matching on a score matrix (reference:
    bounding_box.cc BipartiteMatching). data (..., M, N); returns
    (row_match (..., M), col_match (..., N))."""
    thr = float(attrs.get("threshold", 0.5))
    is_ascend = bool(attrs.get("is_ascend", False))

    flat = data.reshape((-1,) + data.shape[-2:])

    def per_batch(score):
        M, N = score.shape
        s = score if not is_ascend else -score
        thr_ok = (score >= thr) if not is_ascend else (score <= thr)

        def body(carry, _):
            s_cur, rows, cols = carry
            idx = jnp.argmax(s_cur)
            i, j = idx // N, idx % N
            ok = s_cur[i, j] > -jnp.inf
            valid = ok & thr_ok[i, j]
            rows = jnp.where(valid, rows.at[i].set(j), rows)
            cols = jnp.where(valid, cols.at[j].set(i), cols)
            s_cur = jnp.where(valid,
                              s_cur.at[i, :].set(-jnp.inf)
                              .at[:, j].set(-jnp.inf), s_cur)
            return (s_cur, rows, cols), None

        init = (s, -jnp.ones((M,), jnp.float32),
                -jnp.ones((N,), jnp.float32))
        (_, rows, cols), _ = jax.lax.scan(body, init,
                                          None, length=min(M, N))
        return rows, cols

    rows, cols = jax.vmap(per_batch)(flat)
    return rows.reshape(data.shape[:-1]), \
        cols.reshape(data.shape[:-2] + data.shape[-1:])


register("_contrib_bipartite_matching", _bipartite_matching,
         arg_names=("data",),
         defaults={"threshold": 0.5, "is_ascend": False, "topk": -1},
         num_outputs=2)


# ---------------------------------------------------------------------------
# ROI pooling / align
# ---------------------------------------------------------------------------

def _bilinear_at(feat, y, x):
    """Bilinear sample feat (C, H, W) at float coords y, x (...,)."""
    H, W = feat.shape[-2:]
    y = jnp.clip(y, 0.0, H - 1.0)
    x = jnp.clip(x, 0.0, W - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy = y - y0
    wx = x - x0
    v00 = feat[:, y0, x0]
    v01 = feat[:, y0, x1]
    v10 = feat[:, y1, x0]
    v11 = feat[:, y1, x1]
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


def _roi_align(attrs, data, rois):
    """ROIAlign (reference: roi_align.cc). data (B, C, H, W); rois
    (R, 5) [batch_idx, x1, y1, x2, y2]; output (R, C, PH, PW)."""
    ph, pw = [int(s) for s in attrs["pooled_size"]]
    scale = float(attrs.get("spatial_scale", 1.0))
    ratio = int(attrs.get("sample_ratio", -1))
    ns = ratio if ratio > 0 else 2

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * scale, roi[2] * scale, \
            roi[3] * scale, roi[4] * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bh = rh / ph
        bw = rw / pw
        feat = data[b]
        iy = jnp.arange(ph)
        ix = jnp.arange(pw)
        sy = jnp.arange(ns)
        sx = jnp.arange(ns)
        yy = y1 + bh * (iy[:, None, None, None]
                        + (sy[None, None, :, None] + 0.5) / ns)
        xx = x1 + bw * (ix[None, :, None, None]
                        + (sx[None, None, None, :] + 0.5) / ns)
        yy = jnp.broadcast_to(yy, (ph, pw, ns, ns))
        xx = jnp.broadcast_to(xx, (ph, pw, ns, ns))
        vals = _bilinear_at(feat, yy.reshape(-1), xx.reshape(-1))
        vals = vals.reshape(feat.shape[0], ph, pw, ns * ns)
        return vals.mean(axis=-1)

    return jax.vmap(one_roi)(rois)


register("_contrib_ROIAlign", _roi_align, arg_names=("data", "rois"),
         defaults={"pooled_size": (7, 7), "spatial_scale": 1.0,
                   "sample_ratio": -1, "position_sensitive": False},
         aliases=("ROIAlign",))


def _roi_pooling(attrs, data, rois):
    """Max ROI pooling (reference: roi_pooling.cc). Same IO contract as
    ROIAlign but hard max over integer bins."""
    ph, pw = [int(s) for s in attrs["pooled_size"]]
    scale = float(attrs.get("spatial_scale", 1.0))
    H, W = data.shape[2], data.shape[3]

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        feat = data[b]                                  # (C, H, W)
        ys = jnp.arange(H)
        xs = jnp.arange(W)

        def one_bin(i, j):
            by0 = y1 + (i * rh) // ph
            by1 = y1 + ((i + 1) * rh + ph - 1) // ph
            bx0 = x1 + (j * rw) // pw
            bx1 = x1 + ((j + 1) * rw + pw - 1) // pw
            m = ((ys[:, None] >= by0) & (ys[:, None] < by1)
                 & (xs[None, :] >= bx0) & (xs[None, :] < bx1))
            neg = jnp.full(feat.shape, -jnp.inf, feat.dtype)
            sel = jnp.where(m[None], feat, neg)
            best = jnp.max(sel, axis=(1, 2))
            return jnp.where(jnp.any(m), best, 0.0)

        grid_i, grid_j = jnp.meshgrid(jnp.arange(ph), jnp.arange(pw),
                                      indexing="ij")
        vals = jax.vmap(jax.vmap(one_bin))(grid_i, grid_j)
        return jnp.moveaxis(vals, -1, 0)                # (C, PH, PW)

    return jax.vmap(one_roi)(rois)


register("ROIPooling", _roi_pooling, arg_names=("data", "rois"),
         defaults={"pooled_size": (7, 7), "spatial_scale": 1.0})
